"""Multi-chip batched inference: the engine's forward pass spread over
a device mesh.

The single-chip engine (inference/engine.py) is the reference's
per-VM executor rebuilt for TPU; this wraps the same forward in
mesh shardings so one *pod slice* serves a batch: inputs sharded over
`dp` (each chip takes batch/dp images), params replicated over `dp`
and channel-sharded over `tp` (sharding.py). XLA inserts the ICI
collectives; host code stays identical to the single-chip path.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.params_io import init_variables
from ..ops.preprocess import normalize_sharded
from ..models.registry import get_model
from .sharding import partition_params


class ShardedInference:
    """A model compiled for a mesh. Batch size must be a multiple of
    the dp axis (static shapes: one compilation serves every call).

    Two tensor-parallel execution forms:

    - ``param_gather=False`` (Megatron form): compute stays channel-
      sharded end to end; XLA partitions the contractions, so psum
      reduction order differs from a single chip and outputs agree
      only to float tolerance.
    - ``param_gather=True`` (serving-group form, jobs/groups.py):
      weights STAY tp-sharded in HBM (the memory win that lets a
      group hold models no single chip can) but are all-gathered over
      ICI at forward entry, so every dp shard runs the bit-identical
      single-chip program on its batch slice. Outputs are BITWISE
      EQUAL to the single-chip path — the property the worker-group
      pipeline asserts end-to-end (``__graft_entry__.dryrun_multichip``
      part 5) so a degradation/reformation mid-job can never change
      what a query returns.

    The LM serving stack carries BOTH forms too
    (inference/lm_sharded.py): its production group engine keeps
    weights resident tp-sharded with NO per-forward gather — the
    Megatron form, token-exact for greedy decode per
    ``dryrun_multichip`` part 4 — while
    ``LMServer(gather_shardings=...)`` reproduces this class's
    param_gather form as the measured per-dispatch all-gather tax
    (`cluster_lm_sharded` bench). The CNN path here keeps
    param_gather as its default serving form because image batches
    are one forward per batch (one gather), whereas LM decode pays
    the gather EVERY chunk dispatch — which is exactly why the LM
    path must not use it.
    """

    def __init__(
        self,
        model_name: str,
        mesh: Mesh,
        batch_size: int,
        variables: Any = None,
        dtype=jnp.bfloat16,
        seed: int = 0,
        param_gather: bool = False,
    ):
        self.spec = get_model(model_name)
        self.mesh = mesh
        self.param_gather = bool(param_gather)
        dp = mesh.shape.get("dp", 1)
        if batch_size % dp != 0:
            raise ValueError(f"batch_size {batch_size} not divisible by dp={dp}")
        self.batch_size = batch_size
        self.dtype = dtype
        if variables is None:
            variables = init_variables(self.spec, seed=seed, dtype=dtype)
        self.num_classes = int(
            variables["params"]["predictions"]["bias"].shape[-1]
        )
        self._shardings = partition_params(variables, mesh)
        self.variables = jax.device_put(variables, self._shardings)
        model = self.spec.build(dtype=dtype)
        batch_sharding = NamedSharding(mesh, P("dp"))
        out_sharding = NamedSharding(mesh, P("dp"))
        replicated = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), variables
        )

        def fwd(vs, batch_u8):
            if self.param_gather:
                # all-gather the tp-sharded weights, then run the
                # replicated (single-chip-identical) program per dp
                # shard — reduction orders match a single chip exactly
                vs = jax.lax.with_sharding_constraint(vs, replicated)
            x = normalize_sharded(
                batch_u8, self.spec.preprocess, dtype, mesh
            )
            return model.apply(vs, x, train=False)

        self._forward = jax.jit(
            fwd,
            in_shardings=(self._shardings, batch_sharding),
            out_shardings=out_sharding,
        )

    def __call__(self, images_u8: np.ndarray) -> np.ndarray:
        """uint8 (N,H,W,3) -> float32 probs (N,classes); N padded up to
        the compiled batch size."""
        n = images_u8.shape[0]
        bs = self.batch_size
        outs = []
        for start in range(0, n, bs):
            chunk = images_u8[start : start + bs]
            pad = bs - chunk.shape[0]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad, *chunk.shape[1:]), np.uint8)]
                )
            probs = self._forward(self.variables, jnp.asarray(chunk))
            outs.append(np.asarray(probs)[: bs - pad if pad else bs])
        if not outs:
            return np.zeros((0, self.num_classes), np.float32)
        return np.concatenate(outs)[:n]
