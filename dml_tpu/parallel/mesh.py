"""Device-mesh construction from the cluster spec's MeshSpec.

The reference has no device concept at all (CPU TF per VM); here the
mesh is the compute-side analog of its VM ring: `dp` spreads batches
(the reference's inter-VM parallelism, now inter-chip), `tp` shards
weights, `sp` is reserved for sequence parallelism. Axis order puts
`dp` outermost so neighboring devices (fastest ICI links under
`create_device_mesh`'s physical-topology-aware layout) carry the
tensor-parallel collectives, which are the latency-critical ones.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from ..config import MeshSpec

AXES = ("dp", "tp", "sp", "pp", "ep")


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[List[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh from a MeshSpec (axis sizes; -1 = fill)."""
    spec = spec or MeshSpec()
    devices = devices if devices is not None else jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    try:
        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        # topology-aware layout can reject host platforms; plain reshape
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def local_mesh(
    dp: int = -1, tp: int = 1, sp: int = 1, pp: int = 1, ep: int = 1
) -> Mesh:
    """Convenience: mesh over whatever devices this process sees."""
    return make_mesh(MeshSpec(dp=dp, tp=tp, sp=sp, pp=pp, ep=ep))
