"""SLO-aware request front door: per-request ingress for the cluster.

Everything before this entered the cluster as an operator-submitted
batch job (``submit-job <model> <N>`` through the CLI). The north
star is per-request traffic — millions of users each sending ONE
image or ONE prompt with a latency expectation — which is a different
regime: requests arrive open-loop, deadlines differ by class, and the
batch shape the device wants has to be FORMED from whatever is queued
rather than handed down by an operator.

``RequestRouter`` sits in front of JobService on the leader (every
node constructs one; the router role activates with leadership, the
client verbs work anywhere — the same role pattern as JobService):

- **admission** (ingress/slo.py): each request carries an SLO class;
  a request the cluster already knows it cannot serve inside its
  deadline — or whose class queue is at its backpressure limit — is
  SHED with an immediate typed rejection, never a timeout.
- **continuous batch formation**: admitted requests pool in forming
  batches keyed (model, class, session-affinity target). A batch
  dispatches into the ordinary job pipeline when it FILLS, when the
  pipeline is HUNGRY (free slot + empty queue — light load serves at
  single-request latency after a tiny coalescing linger), or when its
  oldest request's deadline-derived slack EXPIRES. One mechanism
  spans the load range: light load gets low latency, heavy load gets
  full device batches. ``formation="fixed"`` pins the naive
  fill-only baseline the bench compares against.
- **dispatch rides the existing pipeline**: a formed batch becomes a
  one-batch job (JobService.ingress_submit) and inherits everything
  the job path already guarantees — fair-share scheduling against
  operator jobs, standby relays, exactly-once completion dedup,
  requeue on worker death, failover.
- **session affinity**: multi-turn LM requests carrying a session id
  are routed toward the worker that served the session's previous
  turn (the node holding its KV state); best-effort — a dead or busy
  node never strands a request.
- **token streaming**: streaming LM requests get their tokens over
  the worker's TCP data plane as they decode (ingress/streaming.py).
- **terminal exactly once**: every admitted request ends in exactly
  one of {completed, rejected(typed)} — pushed (REQUEST_DONE) and
  recoverable by poll (REQUEST_STATUS, the same dropped-push
  discipline as wait_job). After a leader failover, dispatched
  requests complete through the relayed ingress table; requests the
  dead leader never dispatched are answered "unknown" and the client
  converts that into a typed LOST rejection instead of hanging.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..cluster.util import BoundedDict, leader_retry, reap_task
from ..cluster.wire import Message, MsgType
from ..observability import METRICS
from ..tracing import CURRENT_CTXS, TRACER, TraceContext
from .slo import DEFAULT_CLASSES, SLOClass, resolve_class, shed_reason

log = logging.getLogger(__name__)

# request_* metrics family (observability docstring map): the
# per-request analog of the jobs_* C1/C2 counters — admission and
# terminal counters per class, queue-wait and end-to-end latency
# histograms (the bench's p50/p95/p99 source), in-flight gauge, and
# batch-formation quality (fill fraction + formation wall).
_M_ADMITTED = METRICS.counter(
    "request_admitted_total", "requests admitted at the front door, per class")
_M_SHED = METRICS.counter(
    "request_shed_total",
    "requests shed at admission with a typed rejection, per class+reason")
_M_REJECTED = METRICS.counter(
    "request_rejected_total",
    "admitted requests terminally rejected (job failure etc.), per class")
_M_COMPLETED = METRICS.counter(
    "request_completed_total", "requests completed, per class")
_M_DEADLINE_MISS = METRICS.counter(
    "request_deadline_miss_total",
    "completions that landed past their SLO deadline, per class")
_M_QWAIT = METRICS.histogram(
    "request_queue_wait_seconds",
    "admission -> batch dispatch wait, per class")
_M_E2E = METRICS.histogram(
    "request_e2e_latency_seconds",
    "admission -> completion end-to-end latency, per class")
_M_INFLIGHT = METRICS.gauge(
    "request_in_flight", "admitted, not yet terminal, per class")
_M_FILL = METRICS.histogram(
    "request_batch_fill_fraction",
    "formed-batch fill at dispatch (1.0 = full device batch)")
_M_FORMATION = METRICS.histogram(
    "request_batch_formation_seconds",
    "first-enqueue -> dispatch wall per formed batch")
# session-affinity observability: the router's session map is a REAL
# locality signal once the worker-resident KV prefix cache exists
# (inference/kv_cache.py) — a routed-to-holder turn warm-starts, a
# miss re-prefills the whole history. Hits/misses make the signal's
# quality visible; the eviction counter makes `_session_node` bound
# pressure visible (a silently evicted session is a guaranteed cache
# miss on its next turn).
_M_AFF_HITS = METRICS.counter(
    "request_session_affinity_hits_total",
    "session requests routed to their previous turn's live worker")
_M_AFF_MISSES = METRICS.counter(
    "request_session_affinity_misses_total",
    "session requests with no live affinity target (first turn, dead "
    "or demoted holder, or an evicted session row)")
_M_AFF_EVICT = METRICS.counter(
    "request_session_affinity_evictions_total",
    "session->worker rows evicted, per reason= (bound pressure, or a "
    "purge when the holder leaves gracefully / fails)")


def _terminal_kind(terminal: Any) -> str:
    """Classify a settled terminal into its kind (``completed`` /
    ``shed`` / ``rejected`` / ``lost``). Accepts both the full terminal
    dict every settle path carries and the bare ``"lost"`` marker
    ``wait()`` plants when its caller times out unresolved."""
    if isinstance(terminal, str):
        return terminal
    kind = terminal.get("terminal")
    if kind:
        return str(kind)
    return "completed" if terminal.get("ok") else "rejected"


class RequestRejected(RuntimeError):
    """Typed front-door rejection. ``shed=True`` means admission
    control refused it (queue_full / deadline_unmeetable); False means
    a validation or execution failure."""

    def __init__(self, reason: str, slo: str = "", shed: bool = False):
        super().__init__(f"request rejected ({reason})")
        self.reason = reason
        self.slo = slo
        self.shed = shed


@dataclass
class PendingRequest:
    """One admitted request while it lives on the router."""

    id: str
    client: str          # unique_name to push terminals to
    model: str
    slo: SLOClass
    file: str            # store input name (payload's or sampled)
    payload: Optional[bytes]  # inline payload to PUT at dispatch
    session: Optional[str]
    stream: bool
    arrival: float       # monotonic admission time
    deadline: float      # arrival + slo.deadline_s
    #: wall-clock admission time (spans are wall-clocked so cross-node
    #: trees align) and the request's trace context (children of the
    #: root span parent here); ctx is None only for reconstructed
    #: requests whose relay predates tracing
    arrival_wall: float = 0.0
    ctx: Optional[TraceContext] = None


@dataclass
class FormingBatch:
    """Requests coalescing toward one dispatch."""

    model: str
    slo: SLOClass
    affinity: Optional[str]
    opened_at: float
    reqs: List[PendingRequest] = field(default_factory=list)


class BatchFormer:
    """Pure continuous-batch-formation state (deterministic under an
    injected clock; the router drives it from its tick loop).

    ``mode="continuous"`` dispatches a batch when any of:
      - it is FULL (``batch_size_of(model)`` requests),
      - the pipeline is HUNGRY for its model (caller-observed: a free
        slot and no queued batches) and the batch has lingered at
        least ``slo.linger_s * linger_scale`` (the light-load
        coalescing window; scale < 1 when backends adopt mid-flight),
      - its SLACK expired: the oldest request's deadline minus the
        batch's estimated exec (with 50% headroom + 50 ms dispatch
        margin) is now — waiting any longer manufactures SLO misses.

    ``mode="fixed"`` is the naive baseline: dispatch only when full
    (or when the oldest request's deadline has already passed — late,
    but bounded; this is exactly why fixed-size batching loses the
    light-load tail in the bench comparison)."""

    def __init__(
        self,
        batch_size_of: Callable[[str], int],
        est_exec_s: Callable[[str, int], float],
        mode: str = "continuous",
        now: Callable[[], float] = time.monotonic,
        linger_scale: float = 1.0,
    ):
        if mode not in ("continuous", "fixed"):
            raise ValueError(f"unknown formation mode {mode!r}")
        if not (0.0 <= float(linger_scale) <= 1.0):
            raise ValueError(
                f"linger_scale must be in [0, 1], got {linger_scale!r}"
            )
        self.batch_size_of = batch_size_of
        self.est_exec_s = est_exec_s
        self.mode = mode
        self.now = now
        #: scales every class's linger window at the hungry-dispatch
        #: check. The linger exists to amortize batch formation over
        #: co-batchable arrivals — worth real TTFT when the backend
        #: drains each batch before starting the next. When the
        #: serving backends adopt requests into RUNNING batches at
        #: step granularity (LMServer continuous batching), a late
        #: arrival merges into the in-flight grid anyway, so holding
        #: the door open buys nothing: routers fronting adopting
        #: backends shrink it (0 = dispatch the moment the pipeline
        #: is hungry).
        self.linger_scale = float(linger_scale)
        self.forming: Dict[Tuple[str, str, str], FormingBatch] = {}

    def add(self, req: PendingRequest, affinity: Optional[str]) -> None:
        key = (req.model, req.slo.name, affinity or "")
        fb = self.forming.get(key)
        if fb is None:
            fb = FormingBatch(
                model=req.model, slo=req.slo, affinity=affinity,
                opened_at=self.now(),
            )
            self.forming[key] = fb
        fb.reqs.append(req)

    def pending(self) -> int:
        return sum(len(fb.reqs) for fb in self.forming.values())

    def _dispatch_by(self, fb: FormingBatch) -> float:
        est = self.est_exec_s(fb.model, len(fb.reqs))
        oldest = min(r.deadline for r in fb.reqs)
        if self.mode == "fixed":
            return oldest  # the baseline waits for full until too late
        return oldest - 1.5 * est - 0.05

    def due(self, hungry_models: Optional[set] = None) -> List[FormingBatch]:
        """Pop and return every batch that should dispatch now."""
        t = self.now()
        hungry = hungry_models or set()
        out: List[FormingBatch] = []
        for key, fb in list(self.forming.items()):
            size = max(1, self.batch_size_of(fb.model))
            # FULL dispatches in device-batch-sized slices: a burst
            # landing within one tick must not pin a single job's
            # batch_size above the model's configured width (an
            # unconfigured shape — a fresh compile per odd burst size
            # on compiled-shape backends). FIFO order preserved; any
            # remainder keeps forming under the usual rules.
            while len(fb.reqs) >= size:
                out.append(FormingBatch(
                    model=fb.model, slo=fb.slo, affinity=fb.affinity,
                    opened_at=fb.opened_at, reqs=fb.reqs[:size],
                ))
                fb.reqs = fb.reqs[size:]
            if not fb.reqs:
                del self.forming[key]
                continue
            slack_out = t >= self._dispatch_by(fb)
            feed = (
                self.mode == "continuous"
                and fb.model in hungry
                and t - fb.opened_at >= fb.slo.linger_s * self.linger_scale
            )
            if slack_out or feed:
                del self.forming[key]
                out.append(fb)
        return out


@dataclass
class _RequestState:
    req: PendingRequest
    state: str = "forming"  # forming | dispatched
    job_id: Optional[int] = None
    #: the live root span (admission -> terminal); ended exactly once
    #: by whichever terminal path settles the request
    root: Optional[Any] = None
    #: wall clock of the batch dispatch (closes the formation stage in
    #: the terminal's per-stage breakdown)
    dispatched_wall: Optional[float] = None


class RequestRouter:
    """One per node (like JobService): router role while leader,
    client verbs anywhere."""

    def __init__(
        self,
        jobs,
        classes: Optional[Dict[str, SLOClass]] = None,
        formation: str = "continuous",
        tick_s: float = 0.02,
        linger_scale: float = 1.0,
    ):
        self.jobs = jobs
        self.node = jobs.node
        self.store = jobs.store
        self.classes = dict(classes or DEFAULT_CLASSES)
        self.tick_s = tick_s
        # linger_scale < 1 is the knob for deployments whose serving
        # backends adopt requests mid-flight (LM continuous batching,
        # {"overlap": true} specs): the coalescing window shrinks
        # because late arrivals merge into running batches at the
        # next step boundary instead of waiting out a drain
        self.former = BatchFormer(
            batch_size_of=self._batch_size_of,
            est_exec_s=self._est_exec_s,
            mode=formation,
            linger_scale=linger_scale,
        )
        # --- router (leader) state ---
        self._active: Dict[str, _RequestState] = {}
        self._pending_by_class: Dict[str, int] = {}
        self._by_job: Dict[int, List[str]] = {}
        #: terminal records for status re-polls + submit dedup
        self._done: BoundedDict = BoundedDict(5000)
        #: session -> worker that served its last turn (KV locality);
        #: bound-forced evictions are counted (each one guarantees a
        #: prefix-cache miss on that session's next turn)
        self._session_node: BoundedDict = BoundedDict(
            2000, on_evict=lambda _k: _M_AFF_EVICT.inc(reason="bound")
        )
        #: sessions whose binding changed since the last standby relay
        #: (failover-safe affinity: the rows piggyback on INGRESS_RELAY
        #: so a promoted router keeps routing turn N+1 to the worker
        #: holding the session's cached KV)
        self._session_dirty: set = set()
        self._session_flush_t = 0.0
        #: standby: job_id -> relayed request dicts (promotion adopts)
        self._relayed: BoundedDict = BoundedDict(500)
        #: model -> (stamp, sampled input files): pattern matching is
        #: O(store files) and must not run per request at open-loop
        #: rates; sampled inputs are immutable store objects, so a
        #: short TTL is safe
        self._sample_cache: Dict[str, Tuple[float, List[str]]] = {}
        # --- client state ---
        #: request-id salt (see submit): ids must not repeat across a
        #: same-identity restart of this node
        self._rid_salt = secrets.token_hex(4)
        #: bounded: submit()-without-wait() (the documented streaming
        #: flow) leaks one future per request whenever the single
        #: unacked REQUEST_DONE push is dropped — a long-lived node
        #: under loss must not grow this without bound
        self._futs: BoundedDict = BoundedDict(5000)
        self._client_terminal: BoundedDict = BoundedDict(5000)
        #: late COMPLETED terminals for requests already settled as
        #: lost/rejected: work executed and delivered after the
        #: cluster declared it dead — the real exactly-once violation
        #: the failover bench asserts stays zero. (The opposite
        #: direction — a late rejection after a completed settle — is
        #: the promoted router honestly re-terminating relayed
        #: requests whose result bytes died with the old leader; the
        #: first-terminal-wins guard dedups it for clients that got
        #: the original push.)
        self.terminal_conflicts = 0
        #: bounded like every other client-side map: an abandoned
        #: streaming request (caller never drains stream_text) must
        #: not leak its queue for the life of the node
        self._streams: BoundedDict = BoundedDict(1000)
        #: request ids with an ACTIVE data-plane pull: their EOF comes
        #: from the pull task, not the terminal settle — the terminal
        #: can land while the last token chunks are still in flight
        self._stream_pulls: set = set()
        self._form_task: Optional[asyncio.Task] = None
        self._bg: set = set()
        self.shed_count = 0
        self.admit_count = 0
        self._register()
        jobs.on_job_done_cbs.append(self._on_job_done)
        self.node.on_became_leader_cbs.append(self._on_promoted)
        # stale-affinity purge: a departed worker's session rows must
        # go, or turn N+1 chases a ghost instead of cold-routing. The
        # hook fires on EVERY node (router and standby relay copies
        # alike), and the departure kind is read off the universe
        # table: a graceful LEAVE removed the entry before callbacks
        # fire, a crash leaves it in place.
        self.node.on_node_failed_cbs.append(self._purge_sessions_for)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._form_task = asyncio.create_task(
            self._formation_loop(), name=f"{self._me}-ingress-form"
        )

    async def stop(self) -> None:
        # snapshot-before-await (dmllint race-yield-hazard): a start()
        # racing this stop must not have its fresh formation task
        # nulled out after the reap yields
        form, self._form_task = self._form_task, None
        if form is not None:
            await reap_task(form, self._me, "ingress formation")
        for t in list(self._bg):
            t.cancel()

    @property
    def _me(self) -> str:
        return self.node.me.unique_name

    def _register(self) -> None:
        n = self.node
        n.register(MsgType.REQUEST_SUBMIT, self._h_submit)
        n.register(MsgType.REQUEST_STATUS, self._h_status)
        n.register(MsgType.REQUEST_DONE, self._h_done)
        n.register(MsgType.REQUEST_STREAM_READY, self._h_stream_ready)
        n.register(MsgType.INGRESS_RELAY, self._h_ingress_relay)

    def _spawn(self, coro, what: str) -> asyncio.Task:
        t = asyncio.create_task(coro)
        self._bg.add(t)

        def _fin(task: asyncio.Task) -> None:
            self._bg.discard(task)
            if not task.cancelled() and task.exception() is not None:
                log.error("%s: ingress %s failed: %r",
                          self._me, what, task.exception())

        t.add_done_callback(_fin)
        return t

    # ------------------------------------------------------------------
    # cost / shape inputs
    # ------------------------------------------------------------------

    def _batch_size_of(self, model: str) -> int:
        return max(1, self.jobs.scheduler.batch_size_of(model))

    #: slack-shed needs this many measured batches first: the FIRST
    #: batch of a model carries its cold compile (seconds where steady
    #: state is milliseconds), and with sheds blocking new traffic a
    #: one-sample estimate can never heal itself
    MIN_EXEC_SAMPLES = 3

    def _measured_exec_s(self, model: str, n: int) -> Optional[float]:
        """MEASURED per-batch exec from the trailing batch-ACK samples
        (the same stream C2 reads), or None until the model has
        ``MIN_EXEC_SAMPLES`` measured batches on this coordinator.
        Admission slack uses only measured values: trusting the
        registry's reference CPU prior (~50x a real serving batch)
        would shed every interactive request behind any backlog at
        all — and a freshly promoted coordinator starts sample-less,
        where erring permissive beats rejecting live traffic on a
        stale prior. MEDIAN of the trailing window, not mean: the
        cold-compile first batch is a many-second outlier that a mean
        would let poison admission for the next 32 batches."""
        import statistics

        samples = self.jobs.scheduler.latency_samples.get(model)
        if not samples or len(samples) < self.MIN_EXEC_SAMPLES:
            return None
        recent = list(samples)[-32:]
        per_query = statistics.median(
            et / max(1, k) for (_, et, k) in recent
        )
        return max(1e-4, per_query) * max(1, n)

    def _est_exec_s(self, model: str, n: int) -> float:
        """Formation's dispatch-by estimate: measured when available,
        cost-table prior otherwise (an inflated prior only dispatches
        partial batches EARLIER, which is harmless)."""
        measured = self._measured_exec_s(model, n)
        if measured is not None:
            return measured
        cost = self.jobs.scheduler.costs.get(model)
        if cost is None or cost.per_query <= 0:
            return 0.1
        return cost.per_query * max(1, n)

    # ------------------------------------------------------------------
    # router role: admission
    # ------------------------------------------------------------------

    async def _h_submit(self, msg: Message, addr) -> None:
        if not self.node.is_leader:
            return
        d = msg.data
        rid = d.get("rid")
        req_id = str(d.get("id", ""))

        def ack(payload: Dict[str, Any]) -> None:
            self.node.send_unique(
                msg.sender, MsgType.REQUEST_SUBMIT_ACK,
                {"rid": rid, "id": req_id, **payload},
            )

        if not req_id:
            ack({"accepted": False, "reason": "missing_request_id"})
            return
        # idempotent retries: an id we already know keeps its original
        # outcome (re-ACK; a terminal replays its acceptance — the
        # status/push path carries the result)
        if req_id in self._active:
            ack({"accepted": True})
            return
        prior = self._done.get(req_id)
        if prior is not None:
            if prior.get("terminal") == "shed":
                ack({"accepted": False, "reason": prior.get("reason"),
                     "shed": True})
            else:
                ack({"accepted": True})
            return
        slo_name = str(d.get("slo", "interactive"))
        try:
            slo = resolve_class(slo_name, self.classes)
        except KeyError as e:
            ack({"accepted": False, "reason": f"unknown_slo: {e}"})
            return
        try:
            model = self.jobs._canon(str(d.get("model", "")))
        except KeyError:
            ack({"accepted": False, "reason": "unknown_model"})
            return
        payload: Optional[bytes] = None
        stream = bool(d.get("stream"))
        store_name = d.get("store_name")
        if d.get("payload") is not None:
            payload = str(d["payload"]).encode("utf-8")
            file = f"ingress_{req_id}.req"
        elif store_name:
            if not self.store.metadata.replicas_of(str(store_name)):
                ack({"accepted": False, "reason": "unknown_input"})
                return
            file = str(store_name)
        else:
            # no payload: sample a store input the model's patterns
            # match, like the batch-job intake does (shared immutable
            # inputs are the cheap path — no per-request PUT). Cached
            # briefly: fnmatch over the whole store per request would
            # melt at open-loop rates.
            now0 = time.monotonic()
            cached = self._sample_cache.get(model)
            if cached is not None and now0 - cached[0] < 1.0:
                files = cached[1]
            else:
                patterns = self.jobs.model_patterns.get(
                    model, self.jobs.image_patterns
                )
                files = sorted({
                    f for p in patterns
                    for f in self.store.metadata.matching(p)
                })
                # only non-empty listings are cached: negative-caching
                # an empty match would shed 'no_inputs' for the whole
                # TTL after the model's first input lands in the store
                if files:
                    self._sample_cache[model] = (now0, files)
            if not files:
                ack({"accepted": False, "reason": "no_inputs"})
                return
            # streaming requests share sampled inputs like everything
            # else: batch.streams carries a LIST of targets per file,
            # so several streaming requests decoding one input each
            # get their own feed + READY push
            file = files[hash(req_id) % len(files)]
        now = time.monotonic()
        now_wall = time.time()
        # trace head decision at admission (dml_tpu/tracing.py): one
        # seeded-samplable choice per request; the context propagates
        # through every hop the request takes whether sampled or not
        # (unsampled spans surface only as tail exemplars)
        tid = TRACER.new_trace_id()
        trace_sampled = TRACER.head_sample(tid)
        root = TRACER.start_span(
            "request", trace_id=tid, node=self._me,
            sampled=trace_sampled, t0=now_wall,
            labels={"slo": slo.name, "model": model, "id": req_id},
        )
        adm = TRACER.start_span(
            "admission", ctx=root.ctx(), node=self._me, t0=now_wall,
        )
        reason = shed_reason(
            now=now,
            deadline=now + slo.deadline_s,
            pending_in_class=self._pending_by_class.get(slo.name, 0),
            queue_limit=slo.queue_limit,
            backlog_batches=sum(
                len(q) for q in self.jobs.scheduler.queues.values()
            ),
            slots=len(self.jobs.worker_pool()),
            est_batch_exec_s=self._measured_exec_s(
                model, self._batch_size_of(model)
            ),
        )
        if reason is not None:
            self.shed_count += 1
            _M_SHED.inc(slo=slo.name, reason=reason)
            # shed requests observe their (zero) queue wait too: the
            # histogram must describe every request the door saw, not
            # only the ones that dispatched (the overload regime is
            # exactly when the difference matters)
            _M_QWAIT.observe(0.0, slo=slo.name)
            self._done[req_id] = {
                "terminal": "shed", "reason": reason, "slo": slo.name,
                "trace_id": tid,
            }
            adm.end()
            root.label(terminal="shed", reason=reason)
            root.event("shed")  # tail exemplar: captured regardless
            root.end()          # of the sampling decision
            # signal plane: remember this trace as the freshest shed
            # exemplar for the class, so a shed-ratio burn alert can
            # attach the trace that EXPLAINS it
            self.jobs.signal.note_bad_request("shed", slo.name, tid)
            ack({"accepted": False, "reason": reason, "shed": True})
            return
        adm.end()
        req = PendingRequest(
            id=req_id, client=msg.sender, model=model, slo=slo,
            file=file, payload=payload,
            session=d.get("session"), stream=stream,
            arrival=now, deadline=now + slo.deadline_s,
            arrival_wall=now_wall,
            ctx=TraceContext(tid, root.span_id, trace_sampled, key=file),
        )
        affinity = None
        if req.session:
            aff = self._session_node.get(req.session)
            # only a node still in the schedulable pool counts: a dead
            # or demoted holder must not pin the batch to a ghost
            if aff and aff in self.jobs.worker_pool():
                affinity = aff
                _M_AFF_HITS.inc()
            else:
                _M_AFF_MISSES.inc()
        self._active[req_id] = _RequestState(req=req, root=root)
        self._pending_by_class[slo.name] = (
            self._pending_by_class.get(slo.name, 0) + 1
        )
        self.admit_count += 1
        _M_ADMITTED.inc(slo=slo.name)
        _M_INFLIGHT.set(
            self._pending_by_class.get(slo.name, 0), slo=slo.name
        )
        self.former.add(req, affinity)
        ack({"accepted": True})

    # ------------------------------------------------------------------
    # router role: formation + dispatch
    # ------------------------------------------------------------------

    async def _formation_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick_s)
            if not self.node.is_leader:
                if self.former.forming:
                    # demoted WITHOUT crashing (leave/rejoin, lost an
                    # election): requests still forming here were never
                    # dispatched, so no other node can ever complete
                    # them — typed rejection now beats a client-side
                    # lost-conversion later
                    for fb in list(self.former.forming.values()):
                        for r in fb.reqs:
                            self._terminal_reject(r, "leadership_lost")
                    self.former.forming.clear()
                if self._active:
                    # DISPATCHED requests belong to the new leader now
                    # (standby relay / client re-poll complete them) —
                    # no terminal from here, just drop the ledger:
                    # stale _active residue would otherwise inflate
                    # _pending_by_class forever and make a later
                    # re-promotion shed live traffic as queue_full
                    # against phantom in-flight counts
                    self._active.clear()
                    self._by_job.clear()
                    for slo_name in list(self._pending_by_class):
                        self._pending_by_class[slo_name] = 0
                        _M_INFLIGHT.set(0, slo=slo_name)
                continue
            try:
                for fb in self.former.due(self._hungry_models()):
                    self._spawn(
                        self._dispatch_batch(fb),
                        f"dispatch {fb.model}/{fb.slo.name} "
                        f"x{len(fb.reqs)}",
                    )
                now = time.monotonic()
                if (
                    self._session_dirty
                    and now - self._session_flush_t >= self._SESSION_FLUSH_S
                ):
                    self._session_flush_t = now
                    self._flush_sessions()
            except Exception:
                log.exception("%s: ingress formation tick failed", self._me)

    def _hungry_models(self) -> set:
        """Models whose pipeline would idle if we kept lingering: at
        least one free slot and nothing of that model queued."""
        if not self.former.forming:
            return set()
        sched = self.jobs.scheduler
        pool = self.jobs.worker_pool()
        free = any(
            w not in sched.in_progress and w not in sched.prefetch
            for w in pool
        )
        if not free:
            return set()
        return {
            fb.model for fb in self.former.forming.values()
            if not sched.queues.get(fb.model)
        }

    async def _traced_put(self, r: PendingRequest):
        """One request's inline-payload PUT under its trace context
        (gather wraps this into a Task, so the contextvar set is
        task-local and the store's store_put span lands in the right
        trace)."""
        tok = CURRENT_CTXS.set((r.ctx,) if r.ctx is not None else ())
        try:
            return await self.store.put_bytes(
                r.file, r.payload, timeout=15.0
            )
        finally:
            CURRENT_CTXS.reset(tok)

    async def _dispatch_batch(self, fb: FormingBatch) -> None:
        now = time.monotonic()
        reqs = list(fb.reqs)
        # inline payloads land in the replicated store first — workers
        # fetch batch inputs over the ordinary replica path
        puts = [r for r in reqs if r.payload is not None]
        if puts:
            results = await asyncio.gather(
                *(self._traced_put(r) for r in puts),
                return_exceptions=True,
            )
            failed = {
                r.id for r, res in zip(puts, results)
                if isinstance(res, BaseException)
            }
            if failed:
                for r in [r for r in reqs if r.id in failed]:
                    self._terminal_reject(r, "input_store_failed")
                reqs = [r for r in reqs if r.id not in failed]
        if not reqs:
            return
        # file -> [[client, id], ...]: a LIST of targets per input, so
        # two streaming requests naming the same store input in one
        # formed batch each get their own feed + READY push (both the
        # sampling and the store_name paths legitimately share files)
        streams: Dict[str, List[List[Any]]] = {}
        for r in reqs:
            if r.stream:
                streams.setdefault(r.file, []).append([r.client, r.id])
        job_id = self.jobs.scheduler.next_job_id()
        # unique inputs only: two requests naming the same store file
        # must decode ONCE (results and token streams fan back out
        # per-request at completion; a duplicated path would double-
        # feed every stream of that input)
        files = list(dict.fromkeys(r.file for r in reqs))
        # one trace-context wire entry per request rides the batch
        # (next to slo_class): `q` stamps the dispatch wall so the
        # coordinator's first WORKER_TASK_REQUEST send can close the
        # scheduler-side `dispatch` span
        now_wall = time.time()
        traces = [
            {**r.ctx.to_wire(), "q": round(now_wall, 6)}
            for r in reqs if r.ctx is not None
        ]
        try:
            self.jobs.ingress_submit(
                job_id, fb.model, files,
                requester=self._me, affinity=fb.affinity,
                streams=streams or None,
                slo_class=fb.slo.name,
                traces=traces or None,
            )
        except Exception as e:
            log.exception("%s: ingress dispatch of %d reqs failed",
                          self._me, len(reqs))
            for r in reqs:
                self._terminal_reject(r, f"dispatch_failed: {e}")
            return
        ids = []
        for r in reqs:
            st = self._active.get(r.id)
            if st is not None:
                st.state = "dispatched"
                st.job_id = job_id
                st.dispatched_wall = now_wall
            ids.append(r.id)
            _M_QWAIT.observe(now - r.arrival, slo=r.slo.name)
            if r.ctx is not None:
                # formation span: admission -> this dispatch (the
                # front-door queue wait, wall-clocked)
                TRACER.start_span(
                    "formation", ctx=r.ctx, node=self._me,
                    t0=r.arrival_wall,
                    labels={"job": job_id, "slo": r.slo.name},
                ).end(now_wall)
        self._by_job[job_id] = ids
        _M_FILL.observe(len(reqs) / self._batch_size_of(fb.model))
        _M_FORMATION.observe(now - fb.opened_at)
        # standby relay: a promoted router must be able to fan the
        # job's completion back out to the clients (remaining_s keeps
        # deadlines meaningful across the hop)
        sb = self.store.standby_node()
        if sb is not None and sb.unique_name != self._me:
            try:
                self.node.send(
                    sb, MsgType.INGRESS_RELAY,
                    {"job": job_id, "reqs": [
                        [r.id, r.client, r.slo.name, r.file,
                         round(r.deadline - now, 3), r.session or "",
                         int(r.stream),
                         # trace continuity across failover: the
                         # promoted router re-roots the adopted
                         # request under the ORIGINAL trace + root
                         # span id, so its completion carries the same
                         # trace_id and earlier spans keep a parent
                         r.ctx.trace_id if r.ctx else "",
                         r.ctx.span_id if r.ctx else "",
                         int(bool(r.ctx and r.ctx.sampled))]
                        for r in reqs
                    ],
                    # session->worker rows dirtied since the last
                    # relay piggyback here (failover-safe affinity:
                    # turn N+1 after a promotion still routes to the
                    # worker holding the session's cached KV)
                    "sessions": self._take_session_rows()},
                )
            except Exception:
                log.exception("%s: ingress relay of job %d failed",
                              self._me, job_id)

    #: max session rows per relay datagram (UDP control-frame budget)
    _SESSION_RELAY_MAX = 100
    #: standalone session-row flush cadence while dirty rows wait and
    #: no dispatch relay happens to carry them
    _SESSION_FLUSH_S = 0.25

    def _take_session_rows(self) -> List[List[str]]:
        """Pop up to ``_SESSION_RELAY_MAX`` dirtied session->worker
        bindings for a relay payload. Best-effort at-most-once UDP
        like the job relay itself: a dropped row costs the promoted
        router one affinity miss, never correctness."""
        rows: List[List[str]] = []
        while self._session_dirty and len(rows) < self._SESSION_RELAY_MAX:
            s = self._session_dirty.pop()
            w = self._session_node.get(s)
            if w:
                rows.append([s, w])
        return rows

    def _purge_sessions_for(self, uname: str) -> None:
        """Drop every session->worker row pointing at a departed node
        (on_node_failed hook; fires on router and standby copies
        alike). Without this, a graceful scale-in of a worker holding
        KV-prefix sessions leaves ghost rows: turn N+1 would "hit"
        affinity for a node that no longer exists instead of cold-
        routing to a live one. Purged rows leave `_session_dirty` too,
        so a pending relay can't resurrect the binding on the standby."""
        stale = [
            s for s, w in list(self._session_node.items()) if w == uname
        ]
        if not stale:
            return
        # a LEAVE removed the node from the universe table before the
        # callbacks fired; a crash leaves the table entry in place
        reason = (
            "leave"
            if self.node.spec.node_by_unique_name(uname) is None
            else "failure"
        )
        for s in stale:
            self._session_node.pop(s, None)
            self._session_dirty.discard(s)
            _M_AFF_EVICT.inc(reason=reason)
        log.info(
            "%s: purged %d session-affinity rows for departed %s (%s)",
            self._me, len(stale), uname, reason,
        )

    def _flush_sessions(self) -> None:
        """Standalone INGRESS_RELAY carrying only session rows: a
        binding established by the LAST completion before a quiet
        spell (or a leader kill) must not wait for the next dispatch
        to reach the standby."""
        sb = self.store.standby_node()
        if sb is None or sb.unique_name == self._me:
            return
        rows = self._take_session_rows()
        if not rows:
            return
        try:
            self.node.send(sb, MsgType.INGRESS_RELAY, {"sessions": rows})
        except Exception:
            log.exception("%s: ingress session-row flush failed", self._me)

    # ------------------------------------------------------------------
    # router role: completion fan-out
    # ------------------------------------------------------------------

    def _on_job_done(self, st, worker: Optional[str]) -> None:
        ids = self._by_job.pop(st.job_id, None)
        if not ids:
            return
        self._spawn(
            self._complete_job(st, ids, worker),
            f"complete job {st.job_id}",
        )

    async def _complete_job(self, st, ids: List[str], worker) -> None:
        # fast path: inline-results batches carried the results in the
        # completing ACK (no store round trip per job — see
        # Batch.inline_results). The store fallback covers oversized
        # results, which DID take the PUT path. A job completed on a
        # promoted coordinator whose inline copy died with the old
        # leader has neither — its requests get a TYPED rejection
        # below (result_unavailable), never a hollow ok=True with a
        # null result.
        merged: Dict[str, Any] = dict(
            getattr(st, "inline_results", None) or {}
        )
        if not merged and not st.error:
            try:
                listing = await self.store.ls_all(
                    f"output_{st.job_id}_*.json"
                )
                import json as _json

                for name in sorted(listing):
                    part = _json.loads(
                        await self.store.get_bytes(name)
                    )
                    for k, v in part.items():
                        merged.setdefault(k, v)
            except Exception:
                # tolerated like get_output: the worker's PUT may have
                # failed mid-failover; completion still terminates the
                # request (result absent), never hangs it
                log.exception("%s: ingress output fetch for job %d "
                              "failed", self._me, st.job_id)
        now = time.monotonic()
        now_wall = time.time()
        for req_id in ids:
            state = self._active.pop(req_id, None)
            if state is None:
                continue
            r = state.req
            stages = self._request_stages(state, st, now_wall)
            trace_extra = (
                {"trace_id": r.ctx.trace_id, "stages": stages}
                if r.ctx is not None else {}
            )
            self._dec_pending(r.slo.name)
            if st.error:
                self._done[req_id] = {
                    "terminal": "rejected",
                    "reason": f"job_failed: {st.error}", "slo": r.slo.name,
                    **trace_extra,
                }
                _M_REJECTED.inc(slo=r.slo.name, reason="job_failed")
                self._end_root(state, "rejected", now_wall,
                               reason="job_failed")
                try:
                    self.node.send_unique(
                        r.client, MsgType.REQUEST_DONE,
                        {"id": req_id, "ok": False,
                         "reason": f"job_failed: {st.error}",
                         **trace_extra},
                    )
                except Exception:
                    log.exception("%s: ingress job-failed push for %s "
                                  "failed", self._me, req_id)
                continue
            if merged.get(r.file) is None:
                # the job finished but this request's result bytes are
                # gone (inline copy died with the old leader across a
                # failover, or the worker's fallback PUT failed): an
                # explicit typed rejection the client can retry on —
                # completing "ok" with a null result would silently
                # lose the answer
                self._done[req_id] = {
                    "terminal": "rejected",
                    "reason": "result_unavailable", "slo": r.slo.name,
                    **trace_extra,
                }
                _M_REJECTED.inc(slo=r.slo.name,
                                reason="result_unavailable")
                self._end_root(state, "rejected", now_wall,
                               reason="result_unavailable")
                try:
                    self.node.send_unique(
                        r.client, MsgType.REQUEST_DONE,
                        {"id": req_id, "ok": False,
                         "reason": "result_unavailable", **trace_extra},
                    )
                except Exception:
                    log.exception("%s: ingress unavailable push for %s "
                                  "failed", self._me, req_id)
                continue
            e2e = now - r.arrival
            met = now <= r.deadline
            if r.session and worker:
                if self._session_node.get(r.session) != worker:
                    self._session_dirty.add(r.session)
                self._session_node[r.session] = worker
            terminal = {
                "terminal": "completed", "slo": r.slo.name,
                "result": merged.get(r.file),
                "worker": worker, "e2e_ms": round(e2e * 1e3, 2),
                "deadline_met": met,
                **trace_extra,
            }
            try:
                self.node.send_unique(
                    r.client, MsgType.REQUEST_DONE,
                    {"id": req_id, "ok": True, **terminal},
                )
            except Exception:
                # a result too big for one datagram (Message.pack
                # frame cap) must not strand THIS request — the same
                # oversized record in _done would also make every
                # status-ACK unsendable, killing the re-poll recovery
                # path — nor abort the loop and strand the REST of the
                # batch. Degrade to a small typed rejection the client
                # can act on.
                log.exception("%s: ingress completed push for %s "
                              "unsendable; rejecting typed", self._me,
                              req_id)
                self._done[req_id] = {
                    "terminal": "rejected",
                    "reason": "result_too_large", "slo": r.slo.name,
                    **trace_extra,
                }
                _M_REJECTED.inc(slo=r.slo.name,
                                reason="result_too_large")
                self._end_root(state, "rejected", now_wall,
                               reason="result_too_large")
                try:
                    self.node.send_unique(
                        r.client, MsgType.REQUEST_DONE,
                        {"id": req_id, "ok": False,
                         "reason": "result_too_large", **trace_extra},
                    )
                except Exception:
                    log.exception("%s: ingress rejection push for %s "
                                  "failed too", self._me, req_id)
                continue
            _M_COMPLETED.inc(slo=r.slo.name)
            _M_E2E.observe(e2e, slo=r.slo.name)
            if r.ctx is not None:
                # result-return stage: job completion -> DONE push
                TRACER.start_span(
                    "result", ctx=r.ctx, node=self._me, t0=now_wall,
                ).end(time.time())
            if not met:
                # deadline-miss attribution: the counter family's
                # stage= label carries the miss's DOMINANT stage (the
                # one that ate the most wall time), so the metric
                # alone says WHERE the tail is being lost; the miss
                # exemplar trace carries the full breakdown
                dominant = (
                    max(stages, key=lambda k: stages[k])
                    if stages else "unattributed"
                )
                _M_DEADLINE_MISS.inc(slo=r.slo.name, stage=dominant)
                if state.root is not None:
                    state.root.event("deadline_miss")
                    state.root.label(miss_stage=dominant)
                self.jobs.signal.note_bad_request(
                    "deadline_miss", r.slo.name,
                    r.ctx.trace_id if r.ctx is not None else None,
                )
            self._end_root(state, "completed", now_wall,
                           deadline_met=met)
            self._done[req_id] = terminal

    def _request_stages(
        self, state: _RequestState, st, now_wall: float
    ) -> Dict[str, float]:
        """Per-stage seconds for one request's terminal, from what the
        coordinator knows synchronously: the router's own admission/
        dispatch walls plus the batch ACK's carried stage timings
        (``JobState.stage_timing``) — available on a real multi-
        process cluster too, where the worker's spans live on the
        worker. ``dispatch`` is the residual between dispatch and
        completion not explained by the worker's measured exec
        (scheduler queue + wire + ACK latency), floored at zero."""
        r = state.req
        stages: Dict[str, float] = {}
        if state.dispatched_wall and r.arrival_wall:
            stages["formation"] = max(
                0.0, state.dispatched_wall - r.arrival_wall
            )
        timing = getattr(st, "stage_timing", None) or {}
        fetch = float(timing.get("fetch", 0.0))
        backend = float(timing.get("backend", 0.0))
        infer = float(timing.get("infer", 0.0))
        put = float(timing.get("put", 0.0))
        exec_ = float(timing.get("exec", 0.0))
        if timing:
            stages["fetch"] = fetch + max(0.0, backend - infer)
            stages["infer"] = infer
            stages["put"] = put
        if state.dispatched_wall:
            stages["dispatch"] = max(
                0.0, (now_wall - state.dispatched_wall) - max(
                    exec_, fetch + backend + put
                )
            )
        return {k: round(v, 6) for k, v in stages.items()}

    def _end_root(
        self, state: _RequestState, terminal: str, now_wall: float,
        reason: Optional[str] = None, deadline_met: Optional[bool] = None,
    ) -> None:
        """Close a request's root span exactly once with its terminal
        labels (idempotent via Span.end)."""
        root = state.root
        if root is None:
            return
        root.label(terminal=terminal)
        if reason is not None:
            root.label(reason=reason)
        if deadline_met is not None:
            root.label(deadline_met=deadline_met)
        root.end()

    def _terminal_reject(self, r: PendingRequest, reason: str) -> None:
        state = self._active.pop(r.id, None)
        self._dec_pending(r.slo.name)
        # never-dispatched terminals record the queue wait they DID
        # experience: only-completions-observe left the histogram
        # blind to exactly the requests that waited longest and died
        # waiting (optimistic bias under overload)
        _M_QWAIT.observe(
            max(0.0, time.monotonic() - r.arrival), slo=r.slo.name
        )
        self._done[r.id] = {
            "terminal": "rejected", "reason": reason, "slo": r.slo.name,
            **({"trace_id": r.ctx.trace_id} if r.ctx else {}),
        }
        _M_REJECTED.inc(slo=r.slo.name, reason=reason.split(":")[0])
        if state is not None:
            self._end_root(
                state, "rejected", time.time(),
                reason=reason.split(":")[0],
            )
        self.node.send_unique(
            r.client, MsgType.REQUEST_DONE,
            {"id": r.id, "ok": False, "reason": reason,
             **({"trace_id": r.ctx.trace_id} if r.ctx else {})},
        )

    def _dec_pending(self, slo_name: str) -> None:
        n = max(0, self._pending_by_class.get(slo_name, 0) - 1)
        self._pending_by_class[slo_name] = n
        _M_INFLIGHT.set(n, slo=slo_name)

    # ------------------------------------------------------------------
    # router role: status + standby/promotion
    # ------------------------------------------------------------------

    async def _h_status(self, msg: Message, addr) -> None:
        if not self.node.is_leader:
            return
        req_id = str(msg.data.get("id", ""))
        state = self._active.get(req_id)
        done = self._done.get(req_id)
        reply: Dict[str, Any] = {
            "rid": msg.data.get("rid"), "id": req_id,
        }
        if state is not None:
            reply.update({"known": True, "done": False,
                          "state": state.state})
        elif done is not None:
            reply.update({"known": True, "done": True, **done})
        else:
            reply.update({"known": False, "done": False})
        self.node.send_unique(msg.sender, MsgType.REQUEST_STATUS_ACK, reply)

    async def _h_ingress_relay(self, msg: Message, addr) -> None:
        """Standby side: remember which requests ride which job so a
        promotion can fan their completions out, and adopt relayed
        session->worker rows so affinity survives the failover (a
        promoted router otherwise routes every session's next turn to
        a cold peer, turning KV locality into guaranteed misses)."""
        if msg.sender != self.node.leader_unique or self.node.is_leader:
            return
        for row in msg.data.get("sessions") or []:
            if isinstance(row, (list, tuple)) and len(row) >= 2:
                self._session_node[str(row[0])] = str(row[1])
        job = msg.data.get("job")
        if job is None:
            return  # session-row-only flush
        self._relayed[int(job)] = {
            "at": time.monotonic(),
            "reqs": list(msg.data.get("reqs") or []),
        }

    def _on_promoted(self) -> None:
        """Adopt relayed dispatched requests: the promoted coordinator
        finishes their jobs through its shadow queues, and this router
        must complete them — in-flight traffic either completes or is
        explicitly rejected across a failover, never silently lost."""
        if not self._relayed:
            return
        now = time.monotonic()
        adopted = 0
        for job_id, entry in list(self._relayed.items()):
            if job_id in self._by_job:
                continue
            ids = []
            for row in entry["reqs"]:
                (rid_, client, slo_name, file, remaining, session,
                 stream) = row[:7]
                tid, root_sid, tr_sampled = (
                    list(row[7:10]) + ["", "", 0]
                )[:3]
                if rid_ in self._active:
                    continue
                try:
                    slo = resolve_class(slo_name, self.classes)
                except KeyError:
                    slo = SLOClass(slo_name, deadline_s=30.0)
                elapsed = now - entry["at"]
                arrival = (
                    now - max(0.0, slo.deadline_s - float(remaining))
                    - elapsed
                )
                root = None
                ctx = None
                if tid:
                    # re-root the adopted request under the ORIGINAL
                    # trace + root span id: the completion's trace_id
                    # survives the failover, and spans the dead leader
                    # already recorded keep a resolvable parent
                    root = TRACER.start_span(
                        "request", trace_id=str(tid), node=self._me,
                        sampled=bool(tr_sampled),
                        t0=time.time() - max(0.0, now - arrival),
                        labels={"slo": slo.name, "id": rid_,
                                "adopted": 1},
                        span_id=str(root_sid) or None,
                    )
                    ctx = TraceContext(
                        str(tid), root.span_id, bool(tr_sampled),
                        key=file,
                    )
                r = PendingRequest(
                    id=rid_, client=client, model="", slo=slo,
                    file=file, payload=None,
                    session=session or None, stream=bool(stream),
                    arrival=arrival,
                    deadline=now + float(remaining) - elapsed,
                    arrival_wall=time.time() - max(0.0, now - arrival),
                    ctx=ctx,
                )
                self._active[rid_] = _RequestState(
                    req=r, state="dispatched", job_id=job_id, root=root,
                )
                self._pending_by_class[slo.name] = (
                    self._pending_by_class.get(slo.name, 0) + 1
                )
                # the gauge tracks the counter on every path — the
                # failover window is exactly when it must not lie
                _M_INFLIGHT.set(
                    self._pending_by_class[slo.name], slo=slo.name
                )
                ids.append(rid_)
            if ids:
                self._by_job[job_id] = ids
                adopted += len(ids)
                # the job may have already finished on the shadow
                # (retired via ack relays) — complete immediately
                st = self.jobs.scheduler.done_jobs.get(job_id)
                if st is not None:
                    self._on_job_done(st, None)
        self._relayed.clear()
        if adopted:
            log.info("%s: ingress adopted %d in-flight requests across "
                     "failover", self._me, adopted)

    def stats(self) -> Dict[str, Any]:
        """CLI surface: live front-door state."""
        return {
            "mode": self.former.mode,
            "classes": {
                n: {"deadline_s": c.deadline_s,
                    "queue_limit": c.queue_limit}
                for n, c in sorted(self.classes.items())
            },
            "admitted": self.admit_count,
            "shed": self.shed_count,
            "forming": {
                "/".join(k for k in key if k): len(fb.reqs)
                for key, fb in self.former.forming.items()
            },
            "in_flight": dict(self._pending_by_class),
            "sessions_tracked": len(self._session_node),
            "terminal_conflicts": self.terminal_conflicts,
        }

    # ------------------------------------------------------------------
    # client verbs (any node)
    # ------------------------------------------------------------------

    async def submit(
        self,
        model: str,
        slo: str = "interactive",
        payload: Optional[str] = None,
        store_name: Optional[str] = None,
        session: Optional[str] = None,
        stream: bool = False,
        timeout: float = 10.0,
        retries: int = 3,
    ) -> str:
        """Submit one request; returns its id once ADMITTED. A shed or
        invalid request raises ``RequestRejected`` immediately — the
        typed-rejection contract. Retries are idempotent by id."""
        # salted with a per-construction nonce: node.new_rid() counts
        # from 1 per process, so a same-identity client restart (chaos
        # restart_node) would re-mint its predecessor's ids and the
        # leader's _done dedup would hand the NEW request the OLD
        # incarnation's terminal — a stale result served as an answer
        req_id = f"{self.node.new_rid()}~{self._rid_salt}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._futs[req_id] = fut
        if stream:
            self._streams[req_id] = asyncio.Queue()
        data = {
            "id": req_id, "model": model, "slo": slo,
            "session": session, "stream": stream,
        }
        if payload is not None:
            data["payload"] = payload
        if store_name is not None:
            data["store_name"] = store_name
        # the finally owns the cleanup (dmllint race-yield-hazard): a
        # CANCELLED submit — wait_for timeout around submit(), client
        # teardown — skips `except Exception`, and the future + stream
        # queue registered above would leak in _futs/_streams forever
        admitted = rejected = False
        try:
            reply = await leader_retry(
                self.node, MsgType.REQUEST_SUBMIT, data,
                timeout=timeout, retries=retries,
            )
            if not reply.get("accepted"):
                rejected = True  # typed shed: settled, never completes
                raise RequestRejected(
                    str(reply.get("reason", "rejected")), slo=slo,
                    shed=bool(reply.get("shed")),
                )
            admitted = True
            return req_id
        finally:
            if not admitted:
                self._futs.pop(req_id, None)
                self._streams.pop(req_id, None)
                if not rejected and req_id not in self._client_terminal:
                    # the submit may have been ADMITTED with only its
                    # ACK lost — on ANY non-rejection exit (timeout,
                    # no-leader, CANCELLATION — which `except
                    # Exception` never sees) record the client's lost
                    # classification so a later completed push
                    # registers as a terminal conflict (work delivered
                    # after the client declared the request dead)
                    # instead of silently evading the exactly-once
                    # verdict
                    self._client_terminal[req_id] = "lost"

    async def wait(
        self, req_id: str, timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        """Wait for the request's terminal. Primary signal is the
        REQUEST_DONE push; a 1 s status re-poll covers a dropped push
        or a failover (same discipline as JobService.wait_job). A
        coordinator that answers "unknown" five polls in a row lost
        the request to a failover before dispatch — that becomes a
        typed LOST rejection, never a hang."""
        settled = self._client_terminal.get(req_id)
        if settled is not None:
            # already terminal (push landed while the caller was still
            # streaming tokens, or a prior wait classified it) — no
            # future to race, just read the record back
            if isinstance(settled, dict):
                return dict(settled)
            return {"id": req_id, "ok": False,
                    "reason": "lost_failover", "terminal": str(settled)}
        fut = self._futs.setdefault(
            req_id, asyncio.get_running_loop().create_future()
        )

        async def waiter() -> Dict[str, Any]:
            unknown = 0
            while not fut.done():
                try:
                    return await asyncio.wait_for(asyncio.shield(fut), 1.0)
                except asyncio.TimeoutError:
                    try:
                        reply = await self.node.leader_request(
                            MsgType.REQUEST_STATUS, {"id": req_id},
                            timeout=2.0,
                        )
                    except Exception:
                        continue  # no leader reachable; keep waiting
                    if reply.get("done"):
                        self._settle(req_id, {
                            "id": req_id,
                            "ok": reply.get("terminal") == "completed",
                            **{k: v for k, v in reply.items()
                               if k not in ("rid", "known", "done")},
                        })
                    elif not reply.get("known"):
                        unknown += 1
                        if unknown >= 5:
                            self._settle(req_id, {
                                "id": req_id, "ok": False,
                                "reason": "lost_failover",
                                "terminal": "lost",
                            })
                    else:
                        unknown = 0
            return fut.result()

        try:
            return await asyncio.wait_for(waiter(), timeout)
        except asyncio.TimeoutError:
            # the caller is about to classify this request LOST —
            # record it, so a late completed push counts as a terminal
            # conflict rather than settling into an empty record
            if req_id not in self._client_terminal:
                self._client_terminal[req_id] = "lost"
            raise
        finally:
            # unconditional: a wait that timed out unresolved must not
            # leak its future forever. A terminal arriving later still
            # lands in _client_terminal via _settle (bounded), it just
            # no longer has a future to resolve.
            self._futs.pop(req_id, None)

    async def request(
        self,
        model: str,
        slo: str = "interactive",
        timeout: float = 30.0,
        **kw: Any,
    ) -> Dict[str, Any]:
        """submit + wait in one call (CLI / loadgen convenience)."""
        req_id = await self.submit(model, slo=slo, **kw)
        return await self.wait(req_id, timeout=timeout)

    def _settle(self, req_id: str, terminal: Dict[str, Any]) -> None:
        """First terminal wins — exactly once, no matter how many of
        push / poll / lost-detection race to deliver it. A late
        duplicate or downgrade (push + re-poll racing; a promoted
        router re-rejecting a request the old leader completed) is
        benign under this guard; a late COMPLETED for a request
        already settled dead means the cluster executed work after
        declaring it lost — counted, so exactly-once is asserted on
        observations rather than holding by construction here.
        Resolving POPS the future (submit-without-wait — the
        documented streaming flow — must not leak one per request);
        the settled terminal stays readable through wait() via
        ``_client_terminal``."""
        kind = _terminal_kind(terminal)
        prior = self._client_terminal.get(req_id)
        if prior is not None:
            if kind == "completed" and _terminal_kind(prior) != kind:
                self.terminal_conflicts += 1
                log.warning(
                    "%s: conflicting terminal for request %s: settled "
                    "%s, late %s", self._me, req_id,
                    _terminal_kind(prior), kind,
                )
            return
        self._client_terminal[req_id] = dict(terminal)
        fut = self._futs.pop(req_id, None)
        if fut is not None and not fut.done():
            fut.set_result(terminal)
        q = self._streams.get(req_id)
        if q is not None and req_id not in self._stream_pulls:
            # no data-plane pull ever started (non-streaming backend,
            # lost READY push): EOF the listener here so it never
            # hangs. An active pull owns the EOF instead — the
            # terminal can arrive while tokens are still in flight.
            q.put_nowait(None)

    async def _h_done(self, msg: Message, addr) -> None:
        self._settle(str(msg.data.get("id", "")), dict(msg.data))

    async def _h_stream_ready(self, msg: Message, addr) -> None:
        """A worker exposed this request's token stream: pull it over
        the TCP data plane into the local queue as chunks arrive."""
        req_id = str(msg.data.get("id", ""))
        q = self._streams.get(req_id)
        if q is None:
            return  # not a stream request we own (or already settled)
        if req_id in self._stream_pulls:
            return  # duplicate READY (resent task) — one pull at a time
        addr_ = (str(msg.data.get("host")), int(msg.data.get("port", 0)))
        token = str(msg.data.get("token", ""))
        self._stream_pulls.add(req_id)

        async def pull() -> None:
            try:
                async for chunk in self.store.data_plane.fetch_stream(
                    addr_, token
                ):
                    q.put_nowait(chunk.decode("utf-8", errors="replace"))
            except Exception as e:
                log.info("%s: token stream pull for %s ended early: %r",
                         self._me, req_id, e)
            finally:
                self._stream_pulls.discard(req_id)
                q.put_nowait(None)

        self._spawn(pull(), f"stream pull {req_id}")

    async def stream_text(
        self, req_id: str, timeout: float = 30.0,
        on_first: Optional[Callable[[], None]] = None,
        on_chunk: Optional[Callable[[str], None]] = None,
    ) -> List[str]:
        """Collect a streaming request's token chunks until EOF.
        ``on_first`` fires at the first chunk — the client-side TTFT
        probe the multi-turn loadgen phase reads. ``on_chunk`` fires
        per collected chunk (first included) — the loadgen stamps
        these to build per-request TPOT; residue chunks drained at
        EOF fire too, so the stamps reflect when the CLIENT observed
        each token, which is the only TPOT a client can honestly
        claim."""

        def _note(c: str) -> None:
            if on_chunk is not None:
                try:
                    on_chunk(c)
                except Exception as e:
                    log.warning("stream on_chunk hook failed: %r", e)

        q = self._streams.get(req_id)
        if q is None:
            raise KeyError(f"{req_id} is not a streaming request")
        chunks: List[str] = []
        deadline = time.monotonic() + timeout
        try:
            while True:
                item = await asyncio.wait_for(
                    q.get(), max(0.01, deadline - time.monotonic())
                )
                if item is not None and not chunks and on_first is not None:
                    try:
                        on_first()
                    except Exception as e:
                        log.warning("stream on_first hook failed: %r", e)
                if item is None:
                    # terminal settle also EOFs; drain any residue
                    # pushed by a racing pull task
                    while not q.empty():
                        extra = q.get_nowait()
                        if extra is not None:
                            chunks.append(extra)
                            _note(extra)
                    return chunks
                chunks.append(item)
                _note(item)
        finally:
            # the stream is consumed (or abandoned on timeout): drop
            # the queue so drained requests don't occupy the bound
            self._streams.pop(req_id, None)
