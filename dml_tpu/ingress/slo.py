"""SLO classes and admission math for the request front door.

A request enters the cluster with an SLO *class* — a named deadline
tier (``interactive``: sub-second-ish answers for a human waiting;
``batch``: minutes-scale background work). The class decides three
things, all computed here as pure functions so they are deterministic
and unit-testable with injected clocks:

- the request's **deadline** (arrival + ``deadline_s``),
- its **admission**: a request the cluster already knows it cannot
  finish inside the deadline is *shed* at the door with a typed
  rejection (reason string), never left to time out in a queue — the
  open-loop load regime's cardinal rule (arxiv 2605.25645 scores
  exactly this: goodput under an SLO, not raw completions),
- its batch's **dispatch-by time**: the deadline-derived slack that
  continuous batch formation (ingress/router.py) spends waiting for
  co-batchable requests before it must dispatch a partial batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class SLOClass:
    """One deadline tier.

    ``deadline_s``    end-to-end budget from admission to completion.
    ``queue_limit``   max requests of this class pending (forming +
                      dispatched, not yet terminal) before the door
                      sheds — the backpressure bound that keeps a
                      saturated cluster's queue from growing without
                      limit (queue growth under open-loop load is
                      unbounded by construction; only shedding stops it).
    ``linger_s``      minimum time a fresh forming batch waits for
                      co-batchable arrivals when the pipeline is hungry
                      (light-load coalescing window; keep it well under
                      the deadline).
    """

    name: str
    deadline_s: float
    queue_limit: int = 1024
    linger_s: float = 0.02


#: default tiers; operators override per-router
DEFAULT_CLASSES: Dict[str, SLOClass] = {
    "interactive": SLOClass("interactive", deadline_s=2.0,
                            queue_limit=256, linger_s=0.02),
    "batch": SLOClass("batch", deadline_s=30.0,
                      queue_limit=4096, linger_s=0.10),
    # TrainJob step jobs (jobs/train.py): deadline-tolerant throughput
    # work — the scheduler's class weight (train: 0.5, below batch)
    # is what actually protects interactive p99; the loose deadline
    # here just keeps a step job from ever being shed at the door
    "train": SLOClass("train", deadline_s=120.0,
                      queue_limit=64, linger_s=0.10),
}


#: per-class SLO error budgets: the fraction of requests a class may
#: miss/shed before its budget is spent at burn-rate 1.0 (the signal
#: plane's burn monitors divide the observed bad fraction by this).
#: Interactive work gets the tight budget; batch work tolerates more.
DEFAULT_BURN_BUDGETS: Dict[str, float] = {
    "interactive": 0.02,
    "batch": 0.05,
}

#: fallback budget for scopes without an entry (per-model monitors,
#: operator-defined classes): permissive, so an unknown scope cannot
#: page at the interactive threshold by accident
DEFAULT_BURN_BUDGET = 0.05


def burn_budget(name: str) -> float:
    """The SLO error budget for a class (or any monitor scope)."""
    return DEFAULT_BURN_BUDGETS.get(name, DEFAULT_BURN_BUDGET)


def resolve_class(
    name: str, classes: Optional[Dict[str, SLOClass]] = None
) -> SLOClass:
    classes = classes or DEFAULT_CLASSES
    try:
        return classes[name]
    except KeyError:
        raise KeyError(
            f"unknown SLO class {name!r}; known: {sorted(classes)}"
        ) from None


def shed_reason(
    *,
    now: float,
    deadline: float,
    pending_in_class: int,
    queue_limit: int,
    backlog_batches: int,
    slots: int,
    est_batch_exec_s: Optional[float],
) -> Optional[str]:
    """Admission decision for one request; ``None`` admits.

    Two sheds, checked in order:

    - ``queue_full``: the class already has ``queue_limit`` requests
      pending — per-class backpressure, independent of timing.
    - ``deadline_unmeetable``: queue slack is negative. The projected
      finish is ``now + wait + exec`` where the wait is the scheduler
      backlog drained at ``slots`` batches at a time — if that already
      exceeds the deadline, admitting the request only manufactures a
      guaranteed SLO miss that occupies a queue slot other requests
      could use. The estimate is deliberately simple (measured
      per-batch exec x backlog / slots); it errs permissive, because a
      false shed is a user-visible failure while a false admit merely
      becomes one more late completion. ``est_batch_exec_s=None``
      means the model has NO measured exec yet (cold coordinator,
      fresh failover promotion) — the slack check is skipped entirely
      rather than trusted to a prior; only the queue bound sheds.

    A shed gets an immediate typed rejection at the door — never a
    timeout.
    """
    if pending_in_class >= queue_limit:
        return "queue_full"
    if est_batch_exec_s is None:
        return None
    slots = max(1, slots)
    wait_s = (backlog_batches / slots) * est_batch_exec_s
    if now + wait_s + est_batch_exec_s > deadline:
        return "deadline_unmeetable"
    return None
