"""Request front door: SLO-aware per-request ingress for the cluster.

- ``router``: RequestRouter — admission, continuous batch formation,
  session affinity, terminal-exactly-once delivery (the leader-side
  role + the client verbs).
- ``slo``: SLO classes + the pure admission/shedding math.
- ``loadgen``: seeded open-loop arrival traces + tail-latency scoring.
- ``streaming``: per-request LM token streaming over the data plane.
"""

from .loadgen import (  # noqa: F401
    Arrival, ArrivalTrace, Outcome, drive_one, open_loop_trace,
    percentile, run_open_loop, summarize,
)
from .router import BatchFormer, RequestRejected, RequestRouter  # noqa: F401
from .slo import DEFAULT_CLASSES, SLOClass, resolve_class, shed_reason  # noqa: F401
from .streaming import STUB_LM_MODEL, streaming_lm_stub  # noqa: F401
