"""Token streaming for per-request LM serving.

The front door streams LM tokens to the client AS THEY DECODE instead
of after the batch drains: the worker executing an ingress batch
exposes one byte-stream per streaming request on its TCP data plane
(store/data_plane.py ``expose_stream``), tells the client where to
pull (REQUEST_STREAM_READY over the control plane), and feeds tokens
into the stream from the backend's ``on_token`` callback. Bulk bytes
never ride UDP — the same discipline as store transfers and KV-slab
handoffs.

The backend contract mirrors ``on_dispatch`` (jobs/service.py
register_lm): a backend that declares an ``on_token`` parameter opts
in; the service calls it as ``on_token(local_path, text)`` from
whatever thread the backend decodes on (the service hops it back to
the loop). Backends without the parameter serve ingress batches
normally — streaming requests then simply get their tokens with the
final result, a degraded-but-correct mode.

``streaming_lm_stub`` is the jax-free deterministic backend the
chaos.LocalCluster ingress wiring registers: it "decodes" a fixed
token sequence per input with a per-token delay, exercising the full
wire path (expose -> ready push -> TCP pull -> EOF) in tests and the
request_serving bench without a device.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: model name the stub registers under (LocalCluster ingress wiring)
STUB_LM_MODEL = "StubLM"


def streaming_lm_stub(
    per_token_s: float = 0.002, n_tokens: int = 6
) -> Callable:
    """Deterministic streaming LM stub: every input file 'decodes'
    ``n_tokens`` tokens at ``per_token_s`` each, firing ``on_token``
    per token; the final result per file is the full text — so a
    client can assert the streamed tokens concatenate to exactly the
    completed result."""

    async def backend(
        model: str, paths: List[str], on_token: Optional[Callable] = None
    ) -> Tuple[Dict[str, Any], float, None]:
        t0 = time.monotonic()
        results: Dict[str, Any] = {}
        for p in paths:
            parts = []
            for i in range(n_tokens):
                await asyncio.sleep(per_token_s)
                tok = f"tok{i} "
                parts.append(tok)
                if on_token is not None:
                    on_token(p, tok)
            results[p] = {"text": "".join(parts).strip()}
        return results, time.monotonic() - t0, None

    return backend
