"""Open-loop load generator for the request front door.

Closed-loop drivers (submit, wait, submit again — every prior bench
phase worked this way) hide overload: a slow server slows the *driver*
down, so measured latency stays flat while real users would be piling
up. The serving literature scores the **open-loop** regime instead
(arxiv 2605.25645): arrivals follow a fixed trace regardless of how
the server is doing, so queueing delay and shedding show up exactly
as a user population would feel them.

Three pieces, all deterministic:

- ``ArrivalTrace`` / ``open_loop_trace(seed, ...)`` — a seeded
  Poisson-process arrival schedule with per-request model / SLO-class
  / session draws. Same seed => byte-identical trace (asserted by a
  JSON round-trip test); traces serialize so a bench run's workload
  can be re-issued verbatim.
- ``run_open_loop(submit, trace)`` — fire each arrival at its trace
  time (never gated on earlier completions), collect one terminal
  ``Outcome`` per request.
- ``summarize(outcomes, wall_s)`` — tail-latency scoring: p50/p95/p99
  over requests that COMPLETED (shed requests are counted as
  rejections and excluded from the latency distribution — a latency
  percentile that averages in instant rejections would flatter the
  tail), goodput (completions inside their deadline per second), and
  the shed ratio.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import random
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: fire at trace start + ``t`` seconds.

    ``turn`` > 0 marks a CHAINED multi-turn session request
    (`multi_turn_trace` / `run_sessions`): turn N's prompt is the
    session's accumulated history (prior prompts + completions) plus
    this arrival's seeded ``suffix`` token ids, so consecutive turns
    share a growing token prefix — the workload shape the worker-
    resident KV prefix cache (inference/kv_cache.py) exists for.
    ``budget`` is the per-request generation budget those prompts
    carry (0 = the driver's default). Plain open-loop arrivals keep
    turn == 0 and no suffix."""

    t: float
    model: str
    slo: str
    session: Optional[str] = None
    stream: bool = False
    turn: int = 0
    suffix: Optional[Tuple[int, ...]] = None
    budget: int = 0


@dataclass
class ArrivalTrace:
    seed: int
    duration_s: float
    rate_qps: float
    arrivals: List[Arrival] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "duration_s": self.duration_s,
                "rate_qps": self.rate_qps,
                "arrivals": [asdict(a) for a in self.arrivals],
            },
            separators=(",", ":"),
        )

    @classmethod
    def from_json(cls, text: str) -> "ArrivalTrace":
        d = json.loads(text)
        return cls(
            seed=int(d["seed"]),
            duration_s=float(d["duration_s"]),
            rate_qps=float(d["rate_qps"]),
            # suffix rides JSON as a list; the dataclass keeps a tuple
            # so a round-tripped trace re-serializes byte-identically
            arrivals=[
                Arrival(**{
                    **a,
                    "suffix": (
                        tuple(a["suffix"])
                        if a.get("suffix") is not None else None
                    ),
                })
                for a in d["arrivals"]
            ],
        )


def open_loop_trace(
    seed: int,
    duration_s: float,
    rate_qps: float,
    model: str = "stub",
    slo_mix: Optional[Dict[str, float]] = None,
    session_pct: float = 0.0,
    n_sessions: int = 8,
    stream_pct: float = 0.0,
) -> ArrivalTrace:
    """Seeded Poisson arrivals at ``rate_qps`` for ``duration_s``.

    ``slo_mix`` maps class name -> weight (default all interactive);
    ``session_pct`` percent of requests carrying a session id (drawn
    from ``n_sessions`` stable ids — multi-turn affinity traffic);
    ``stream_pct`` percent requesting token streaming. Every draw
    comes from one ``random.Random(seed)`` in arrival order, so the
    whole trace — times, classes, sessions — replays identically."""
    rng = random.Random(seed)
    mix = list((slo_mix or {"interactive": 1.0}).items())
    total_w = sum(w for _, w in mix) or 1.0
    arrivals: List[Arrival] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_qps) if rate_qps > 0 else duration_s
        if t >= duration_s:
            break
        x = rng.random() * total_w
        slo = mix[-1][0]
        for name, w in mix:
            if x < w:
                slo = name
                break
            x -= w
        session = (
            f"s{rng.randrange(n_sessions)}"
            if rng.random() * 100.0 < session_pct else None
        )
        stream = rng.random() * 100.0 < stream_pct
        arrivals.append(Arrival(
            t=round(t, 6), model=model, slo=slo,
            session=session, stream=stream,
        ))
    return ArrivalTrace(
        seed=seed, duration_s=duration_s, rate_qps=rate_qps,
        arrivals=arrivals,
    )


def diurnal_trace(
    seed: int,
    duration_s: float,
    base_qps: float,
    peak_qps: float,
    model: str = "stub",
    slo_mix: Optional[Dict[str, float]] = None,
    ramp_frac: float = 0.25,
    plateau_frac: float = 0.35,
    session_pct: float = 0.0,
    n_sessions: int = 8,
    stream_pct: float = 0.0,
) -> ArrivalTrace:
    """Seeded RAMP–PLATEAU–TROUGH arrivals — the diurnal curve the
    autoscaler bench scores static provisioning against. The rate
    envelope climbs from ``base_qps`` to ``peak_qps`` over the first
    ``ramp_frac`` of the run, holds the peak for ``plateau_frac``,
    ramps back down over another ``ramp_frac``, and idles at
    ``base_qps`` for the remaining trough. Arrivals are a
    non-homogeneous Poisson process drawn by THINNING against the peak
    rate — candidate gaps at ``peak_qps``, each kept with probability
    ``rate(t)/peak_qps`` — so every draw still comes from one
    ``random.Random(seed)`` in arrival order and the whole trace
    replays byte-identically (same JSON round-trip contract as
    ``open_loop_trace``). Per-request SLO-class / session / stream
    draws match ``open_loop_trace``'s."""
    rng = random.Random(seed)
    base = max(0.0, float(base_qps))
    peak = max(base, float(peak_qps))
    r = max(0.0, float(ramp_frac)) * duration_s
    p = max(0.0, float(plateau_frac)) * duration_s

    def rate(t: float) -> float:
        if r > 0 and t < r:
            return base + (peak - base) * (t / r)
        if t < r + p:
            return peak
        if r > 0 and t < 2 * r + p:
            return peak - (peak - base) * ((t - r - p) / r)
        return base

    mix = list((slo_mix or {"interactive": 1.0}).items())
    total_w = sum(w for _, w in mix) or 1.0
    arrivals: List[Arrival] = []
    t = 0.0
    while peak > 0:
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() * peak >= rate(t):
            continue  # thinned: the envelope is below peak here
        x = rng.random() * total_w
        slo = mix[-1][0]
        for name, w in mix:
            if x < w:
                slo = name
                break
            x -= w
        session = (
            f"s{rng.randrange(n_sessions)}"
            if rng.random() * 100.0 < session_pct else None
        )
        stream = rng.random() * 100.0 < stream_pct
        arrivals.append(Arrival(
            t=round(t, 6), model=model, slo=slo,
            session=session, stream=stream,
        ))
    mean = len(arrivals) / duration_s if duration_s > 0 else 0.0
    return ArrivalTrace(
        seed=seed, duration_s=float(duration_s),
        rate_qps=round(mean, 6), arrivals=arrivals,
    )


def multi_turn_trace(
    seed: int,
    n_sessions: int,
    turns: int,
    model: str,
    *,
    slo: str = "interactive",
    start_gap_s: float = 0.5,
    think_s: float = 0.5,
    suffix_len: int = 8,
    vocab: int = 61,
    budget: int = 16,
) -> ArrivalTrace:
    """Seeded GROWING-HISTORY session trace: ``n_sessions`` sessions of
    ``turns`` chained turns each. Turn N's prompt is the session's
    prior prompts + completions plus this turn's seeded ``suffix``
    (drawn from ``vocab``), so consecutive turns extend a shared token
    prefix — the prefix-cache workload. Arrival times stagger session
    starts by ``start_gap_s`` with ``think_s`` between a session's
    turns; `run_sessions` treats them as EARLIEST-fire times (a turn
    additionally waits for its predecessor's completion — closed-loop
    within a session, open-loop across sessions). Same seed =>
    byte-identical trace; JSON round-trips like `open_loop_trace`'s."""
    rng = random.Random(seed)
    arrivals: List[Arrival] = []
    for s in range(n_sessions):
        t0 = round(s * start_gap_s + rng.random() * 0.1, 6)
        for k in range(turns):
            arrivals.append(Arrival(
                t=round(t0 + k * think_s, 6), model=model, slo=slo,
                session=f"mt{seed}s{s}", stream=True, turn=k + 1,
                suffix=tuple(
                    rng.randrange(vocab) for _ in range(suffix_len)
                ),
                budget=int(budget),
            ))
    arrivals.sort(key=lambda a: (a.t, a.session, a.turn))
    duration = max((a.t for a in arrivals), default=0.0) + think_s
    rate = len(arrivals) / duration if duration > 0 else 0.0
    return ArrivalTrace(
        seed=seed, duration_s=round(duration, 6),
        rate_qps=round(rate, 6), arrivals=arrivals,
    )


# ----------------------------------------------------------------------
# outcomes + scoring
# ----------------------------------------------------------------------

#: terminal states (exactly one per request — the front-door contract)
TERMINAL_COMPLETED = "completed"
TERMINAL_SHED = "shed"          # typed rejection at the admission door
TERMINAL_REJECTED = "rejected"  # typed rejection after admission
TERMINAL_LOST = "lost"          # coordinator lost it (failover); the
                                # client converted silence into a typed
                                # terminal — still counted as rejection


@dataclass
class Outcome:
    """One request's terminal record."""

    slo: str
    terminal: str
    e2e_s: Optional[float] = None  # submit -> terminal (completions)
    deadline_met: bool = False
    reason: Optional[str] = None
    model: str = ""
    session: Optional[str] = None
    worker: Optional[str] = None
    #: completions only: the terminal carried actual result payload.
    #: A completed outcome WITHOUT one is the silent-loss failure the
    #: front door types as result_unavailable instead — the failover
    #: bench asserts this never reads False on a completion.
    has_result: bool = False
    #: distributed-tracing join keys (dml_tpu/tracing.py): the trace
    #: id minted at admission and the router's terminal-carried
    #: per-stage seconds — `summarize` joins completions against
    #: pulled cluster traces by trace_id, with `stages` as the
    #: fallback when a trace was sampled away or evicted
    trace_id: Optional[str] = None
    stages: Optional[Dict[str, float]] = None
    #: multi-turn session fields (`run_sessions`): which turn this
    #: outcome belongs to (0 = not chained) and the client-side
    #: time-to-first-token measured at the first streamed chunk —
    #: the warm-vs-cold number the prefix-cache bench phase scores
    turn: int = 0
    ttft_s: Optional[float] = None
    #: time-per-output-token: mean inter-chunk gap over the request's
    #: streamed tokens, (last stamp - first stamp) / (chunks - 1),
    #: from client-side `on_chunk` stamps (None when the request
    #: streamed < 2 chunks or didn't stream). TTFT scores the prefill
    #: + queue story; TPOT scores the DECODE loop — speculative
    #: decoding moves this one.
    tpot_s: Optional[float] = None


def percentile(sorted_vals: Sequence[float], p: float) -> float:
    """Linear-interpolation percentile over an already-sorted sample
    (the NIST/numpy 'linear' definition): rank ``p/100 * (n-1)`` is
    interpolated between its floor and ceiling neighbors. The test
    fixture hand-computes these."""
    if not sorted_vals:
        return math.nan
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    rank = (p / 100.0) * (len(sorted_vals) - 1)
    lo = math.floor(rank)
    hi = math.ceil(rank)
    frac = rank - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


def summarize(
    outcomes: Sequence[Outcome], wall_s: float,
    trace_stages: Optional[Dict[str, Dict[str, float]]] = None,
) -> Dict[str, Any]:
    """Tail-latency + goodput scorecard over one open-loop run.

    Latency percentiles are computed over COMPLETED requests only:
    shed/rejected/lost requests are terminal *rejections* — they are
    counted (``shed`` / ``rejected`` and the ``shed_ratio``) but
    excluded from the latency distribution, because an immediate
    rejection's near-zero "latency" would deflate the percentiles of
    the requests the cluster actually served. Goodput counts only
    completions that made their deadline.

    ``trace_stages`` joins completions against collected traces
    (trace_id -> per-stage seconds, e.g. ``tracing.stage_breakdown``
    over a ``pull_cluster_traces`` result); each completion falls back
    to its terminal-carried ``stages`` when its trace was sampled away
    or evicted. When any join lands, the scorecard gains a
    ``p99_attribution`` block: the mean per-stage breakdown of the
    p99 COHORT (completions at/above the p99 latency) — which hop ate
    the tail, not just how long the tail is."""
    out: Dict[str, Any] = {"n": len(outcomes), "wall_s": round(wall_s, 3)}
    by_class: Dict[str, List[Outcome]] = {}
    for o in outcomes:
        by_class.setdefault(o.slo, []).append(o)

    def score(rows: Sequence[Outcome]) -> Dict[str, Any]:
        lat = sorted(
            o.e2e_s for o in rows
            if o.terminal == TERMINAL_COMPLETED and o.e2e_s is not None
        )
        tpot = sorted(
            o.tpot_s for o in rows
            if o.terminal == TERMINAL_COMPLETED and o.tpot_s is not None
        )
        completed = sum(1 for o in rows if o.terminal == TERMINAL_COMPLETED)
        shed = sum(1 for o in rows if o.terminal == TERMINAL_SHED)
        rejected = sum(
            1 for o in rows
            if o.terminal in (TERMINAL_REJECTED, TERMINAL_LOST)
        )
        good = sum(
            1 for o in rows
            if o.terminal == TERMINAL_COMPLETED and o.deadline_met
        )
        return {
            "n": len(rows),
            "completed": completed,
            "shed": shed,
            "rejected": rejected,
            "goodput_qps": round(good / wall_s, 2) if wall_s > 0 else 0.0,
            "shed_ratio": (
                round((shed + rejected) / len(rows), 4) if rows else 0.0
            ),
            "latency_ms": {
                "p50": round(percentile(lat, 50) * 1e3, 1) if lat else None,
                "p95": round(percentile(lat, 95) * 1e3, 1) if lat else None,
                "p99": round(percentile(lat, 99) * 1e3, 1) if lat else None,
            },
            # decode-loop tail, from client-observed inter-chunk
            # stamps (Outcome.tpot_s); None keys when the run didn't
            # stream — e2e latency folds queue+prefill+decode
            # together, TPOT isolates the decode loop that
            # speculative decoding accelerates
            "tpot_ms": {
                "p50": round(percentile(tpot, 50) * 1e3, 2) if tpot else None,
                "p95": round(percentile(tpot, 95) * 1e3, 2) if tpot else None,
                "p99": round(percentile(tpot, 99) * 1e3, 2) if tpot else None,
            },
        }

    out.update(score(outcomes))
    out["by_class"] = {c: score(rows) for c, rows in sorted(by_class.items())}
    attrib = _p99_attribution(outcomes, trace_stages)
    if attrib is not None:
        out["p99_attribution"] = attrib
    turn_block = _by_turn(outcomes)
    if turn_block is not None:
        out["by_turn"] = turn_block
    return out


def _by_turn(outcomes: Sequence[Outcome]) -> Optional[Dict[str, Any]]:
    """Per-turn TTFT scorecard over chained session outcomes (None
    when the run carried no multi-turn traffic). Turn 1 pays the cold
    prefill either way; turns >= 2 are where a prefix-cache warm
    start shows up as a TTFT drop."""
    rows = [o for o in outcomes if o.turn > 0]
    if not rows:
        return None
    by: Dict[int, List[Outcome]] = {}
    for o in rows:
        by.setdefault(o.turn, []).append(o)
    out: Dict[str, Any] = {}
    for turn, grp in sorted(by.items()):
        tt = sorted(
            o.ttft_s for o in grp
            if o.terminal == TERMINAL_COMPLETED and o.ttft_s is not None
        )
        out[str(turn)] = {
            "n": len(grp),
            "completed": sum(
                1 for o in grp if o.terminal == TERMINAL_COMPLETED
            ),
            "ttft_ms": {
                "p50": round(percentile(tt, 50) * 1e3, 1) if tt else None,
                "mean": (
                    round(sum(tt) / len(tt) * 1e3, 1) if tt else None
                ),
            },
        }
    return out


def _p99_attribution(
    outcomes: Sequence[Outcome],
    trace_stages: Optional[Dict[str, Dict[str, float]]],
) -> Optional[Dict[str, Any]]:
    """Join completions against traces and attribute the p99 cohort's
    time to stages (None when nothing joins — no tracing ran)."""
    from ..tracing import cohort_attribution

    completed = [
        o for o in outcomes
        if o.terminal == TERMINAL_COMPLETED and o.e2e_s is not None
    ]
    if not completed:
        return None
    joined: List[Tuple[Outcome, Dict[str, float]]] = []
    for o in completed:
        stages = None
        if trace_stages and o.trace_id:
            stages = trace_stages.get(o.trace_id)
        if not stages:
            stages = o.stages
        if stages:
            joined.append((o, {
                k: float(v) for k, v in stages.items()
                if isinstance(v, (int, float))
            }))
    if not joined:
        return None
    lats = sorted(o.e2e_s for o in completed)
    p99v = percentile(lats, 99)
    cohort = [(o, s) for o, s in joined if o.e2e_s >= p99v]
    if not cohort:  # every p99-cohort completion failed to join:
        # report the slowest joined completion rather than nothing
        cohort = sorted(joined, key=lambda t: t[0].e2e_s)[-1:]
    attrib = cohort_attribution(
        [s for _, s in cohort], [o.e2e_s for o, _ in cohort]
    )
    attrib["p99_ms"] = round(p99v * 1e3, 1)
    attrib["joined"] = len(joined)
    attrib["completed"] = len(completed)
    attrib["join_fraction"] = round(len(joined) / len(completed), 4)
    return attrib


async def drive_one(
    ingress,
    a: Arrival,
    *,
    store_name: Optional[str] = None,
    submit_timeout: float = 8.0,
    wait_timeout: float = 45.0,
    deadline_by_class: Optional[Dict[str, float]] = None,
    now: Callable[[], float] = time.monotonic,
) -> Outcome:
    """Drive ONE arrival through a RequestRouter's client verbs to a
    terminal Outcome — the shared submit/wait/classify mapping the
    bench's open-loop phases and the CLI ``request-load`` verb both
    use (one copy, so a LOST terminal is classified identically
    everywhere). e2e is measured CLIENT-side (includes the submit
    round trip); ``deadline_by_class`` overrides the router's
    deadline_met with the client-side clock when provided.
    ``store_name`` pins the request to a specific pre-put store input
    instead of the router's sampled default — drivers that need
    per-request work (the diurnal provisioning probe) spread requests
    over distinct inputs so batch-level file dedup cannot collapse
    their cost."""
    from .router import RequestRejected

    t0 = now()
    try:
        rid = await ingress.submit(
            a.model, slo=a.slo, store_name=store_name,
            session=a.session, stream=a.stream,
            timeout=submit_timeout,
        )
    except RequestRejected as e:
        return Outcome(
            slo=a.slo,
            terminal=TERMINAL_SHED if e.shed else TERMINAL_REJECTED,
            reason=e.reason, model=a.model, session=a.session,
        )
    except Exception as e:
        return Outcome(slo=a.slo, terminal=TERMINAL_LOST, reason=repr(e),
                       model=a.model, session=a.session)
    try:
        term = await ingress.wait(rid, timeout=wait_timeout)
    except Exception as e:
        return Outcome(slo=a.slo, terminal=TERMINAL_LOST,
                       reason=f"wait: {e!r}", model=a.model,
                       session=a.session)
    e2e = now() - t0
    if term.get("ok"):
        if deadline_by_class and a.slo in deadline_by_class:
            met = e2e <= deadline_by_class[a.slo]
        else:
            met = bool(term.get("deadline_met"))
        return Outcome(
            slo=a.slo, terminal=TERMINAL_COMPLETED, e2e_s=e2e,
            deadline_met=met, model=a.model, session=a.session,
            worker=term.get("worker"),
            has_result=term.get("result") is not None,
            trace_id=term.get("trace_id"),
            stages=(term.get("stages")
                    if isinstance(term.get("stages"), dict) else None),
        )
    return Outcome(
        slo=a.slo,
        terminal=(TERMINAL_LOST if term.get("terminal") == "lost"
                  else TERMINAL_REJECTED),
        reason=term.get("reason"), model=a.model, session=a.session,
        trace_id=term.get("trace_id"),
    )


async def run_sessions(
    ingress,
    trace: ArrivalTrace,
    *,
    submit_timeout: float = 8.0,
    wait_timeout: float = 45.0,
    turn_retries: int = 3,
    now: Callable[[], float] = time.monotonic,
) -> Tuple[List[Outcome], float, Dict[str, List[List[int]]]]:
    """Drive a `multi_turn_trace` through a RequestRouter's client
    verbs: sessions run concurrently (open-loop starts), but WITHIN a
    session turn N+1 submits only after turn N completes — its prompt
    is the accumulated history (prior prompts + completions) plus the
    arrival's seeded suffix, shipped as an inline prompt-file payload
    with the turn's budget directive. Every turn streams; TTFT is the
    client-observed first streamed chunk.

    A failed turn retries up to ``turn_retries`` times (greedy decode
    is deterministic, so a retry cannot fork the transcript — the
    failover case leans on this); a turn that never completes aborts
    its session, with the remaining turns recorded as rejections so
    terminals stay exhaustive. Returns (outcomes, wall seconds,
    {session: completion token lists in turn order}) — the transcript
    map is what the bench's warm-vs-cold equality verdict compares."""
    from .router import RequestRejected

    t0 = now()
    outcomes: List[Outcome] = []
    transcripts: Dict[str, List[List[int]]] = {}
    by_session: Dict[str, List[Arrival]] = {}
    for a in trace.arrivals:
        if not a.session or a.turn <= 0:
            raise ValueError("run_sessions wants multi_turn_trace arrivals")
        by_session.setdefault(a.session, []).append(a)

    async def one_turn(
        a: Arrival, history: List[int]
    ) -> Tuple[Outcome, Optional[List[int]]]:
        prompt = history + list(a.suffix or ())
        budget = int(a.budget) or 16
        payload = (
            f"# max_new_tokens: {budget}\n"
            + " ".join(str(t) for t in prompt)
        )
        t_sub = now()
        try:
            rid = await ingress.submit(
                a.model, slo=a.slo, payload=payload, session=a.session,
                stream=True, timeout=submit_timeout,
            )
        except RequestRejected as e:
            return Outcome(
                slo=a.slo,
                terminal=TERMINAL_SHED if e.shed else TERMINAL_REJECTED,
                reason=e.reason, model=a.model, session=a.session,
                turn=a.turn,
            ), None
        except Exception as e:
            return Outcome(
                slo=a.slo, terminal=TERMINAL_LOST, reason=repr(e),
                model=a.model, session=a.session, turn=a.turn,
            ), None
        ttft_box: List[float] = []
        chunk_ts: List[float] = []
        stream_task = asyncio.ensure_future(ingress.stream_text(
            rid, timeout=wait_timeout,
            on_first=lambda: ttft_box.append(now() - t_sub),
            on_chunk=lambda _c: chunk_ts.append(now()),
        ))
        try:
            term = await ingress.wait(rid, timeout=wait_timeout)
        except Exception as e:
            stream_task.cancel()
            return Outcome(
                slo=a.slo, terminal=TERMINAL_LOST, reason=f"wait: {e!r}",
                model=a.model, session=a.session, turn=a.turn,
            ), None
        try:
            await stream_task  # EOF rides the terminal settle
        except Exception as e:
            # TTFT may be missing; the terminal is authoritative
            logging.getLogger(__name__).debug(
                "session stream drain ended early: %r", e
            )
        e2e = now() - t_sub
        result = term.get("result") if term.get("ok") else None
        toks = (result or {}).get("tokens")
        if term.get("ok") and isinstance(toks, list):
            return Outcome(
                slo=a.slo, terminal=TERMINAL_COMPLETED, e2e_s=e2e,
                deadline_met=bool(term.get("deadline_met")),
                model=a.model, session=a.session,
                worker=term.get("worker"), has_result=True,
                trace_id=term.get("trace_id"), turn=a.turn,
                ttft_s=ttft_box[0] if ttft_box else None,
                tpot_s=(
                    (chunk_ts[-1] - chunk_ts[0]) / (len(chunk_ts) - 1)
                    if len(chunk_ts) >= 2 else None
                ),
            ), [int(t) for t in toks]
        return Outcome(
            slo=a.slo,
            terminal=(TERMINAL_LOST if term.get("terminal") == "lost"
                      else TERMINAL_REJECTED),
            reason=term.get("reason") or "no_tokens_in_result",
            model=a.model, session=a.session, turn=a.turn,
        ), None

    async def one_session(sess: str, turns_list: List[Arrival]) -> None:
        history: List[int] = []
        transcripts[sess] = []
        for i, a in enumerate(sorted(turns_list, key=lambda x: x.turn)):
            delay = a.t - (now() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            o: Optional[Outcome] = None
            toks: Optional[List[int]] = None
            for attempt in range(turn_retries + 1):
                o, toks = await one_turn(a, history)
                if o.terminal == TERMINAL_COMPLETED or attempt == turn_retries:
                    break
                await asyncio.sleep(0.25 * (attempt + 1))
            assert o is not None
            outcomes.append(o)
            if o.terminal != TERMINAL_COMPLETED or toks is None:
                # the chain is broken — later prompts would diverge
                # from the deterministic transcript, so the session
                # aborts and its remaining turns settle as typed
                # rejections (terminals stay exhaustive for scoring)
                for rest in sorted(turns_list, key=lambda x: x.turn)[i + 1:]:
                    outcomes.append(Outcome(
                        slo=rest.slo, terminal=TERMINAL_REJECTED,
                        reason="session_aborted", model=rest.model,
                        session=sess, turn=rest.turn,
                    ))
                return
            # history grows by this turn's prompt suffix + completion
            transcripts[sess].append(toks)
            history = history + list(a.suffix or ()) + toks

    await asyncio.gather(
        *(one_session(s, rows) for s, rows in by_session.items())
    )
    return outcomes, now() - t0, transcripts


async def run_open_loop(
    submit: Callable[[Arrival], Awaitable[Outcome]],
    trace: ArrivalTrace,
    *,
    now: Callable[[], float] = time.monotonic,
) -> Tuple[List[Outcome], float]:
    """Drive the trace open-loop: each arrival fires at its scheduled
    offset from the run start whether or not earlier requests came
    back (that is the whole point). ``submit`` handles one request
    end-to-end and must ALWAYS return a terminal ``Outcome`` — the
    front door's typed-rejection contract means it never has to guess.
    Returns (outcomes in arrival order, wall seconds to last terminal).
    """
    t0 = now()
    results: List[Optional[Outcome]] = [None] * len(trace.arrivals)

    async def one(i: int, a: Arrival) -> None:
        delay = a.t - (now() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        results[i] = await submit(a)

    await asyncio.gather(
        *(one(i, a) for i, a in enumerate(trace.arrivals))
    )
    wall = now() - t0
    return [o for o in results if o is not None], wall
