"""End-to-end distributed request tracing: spans, context propagation,
per-node flight recorder, cluster collection, tail attribution.

The metrics registry (observability.py) answers "how is the cluster
doing" in aggregate — exactly the coordinator console the reference
paper ships. What it cannot answer is "where did THIS request's time
go": a p99 outlier or a deadline miss crosses the front door, the
coordinator's batch former, the scheduler, a worker's fetch/infer/put
pipeline, and (for disaggregated LM serving) a prefill peer and a KV
handoff — four or more processes, none of which holds the whole story.
This module is the per-request causality layer:

- **Span** — one named, wall-clocked interval on one node, belonging
  to a trace (``trace_id``) under a parent span. Span NAMES are a
  closed registry (``SPAN_NAMES``): the stage names the attribution
  table reports are the same constants the instrumentation emits, and
  tools/dmllint.py (rule ``drift-span-names``) fails the build when a
  ``start_span("...")`` call site uses a name this registry doesn't
  declare — stage names cannot silently drift.
- **TraceContext** — the (trace_id, parent span, sampled) triple that
  rides the wire next to ``slo_class``: REQUEST_SUBMIT mints it at
  admission (seeded head-sampling decision), the formed batch carries
  one context per request through ``ingress_submit`` → scheduler →
  WORKER_TASK_REQUEST → LM_PREFILL_REQUEST → back out via
  REQUEST_DONE, so one trace stitches the full cross-node span tree.
- **Flight recorder** (``Tracer``) — a bounded ring buffer of finished
  spans per process, plus ALWAYS-ON capture (regardless of the head
  sampling decision) of the slowest-K request roots and of every span
  carrying a tail-exemplar event (``deadline_miss`` / ``shed`` /
  ``requeue`` / ``fallback``): the exemplars that explain the tail are
  never sampled away.
- **TRACE_PULL** (cluster/node.py) — leader aggregation of every
  node's recorder with the same tier-by-tier datagram degradation as
  METRICS_PULL; ``assemble_traces`` stitches the pulled spans into
  per-trace trees and ``chrome_trace`` exports them for
  ``chrome://tracing`` / Perfetto. CLI: ``trace [dump|pull|chrome]``.
- **Attribution** — ``stage_breakdown`` folds one trace's spans into
  per-stage seconds; ``ingress/loadgen.summarize`` joins completions
  against these to report where the p99 cohort's time went
  (queue-wait vs formation vs dispatch vs prefill vs handoff vs
  decode vs result-return), and the ``request_serving`` bench section
  embeds the result as its ``tracing`` block (claim_check-gated).

Overhead discipline: every recorder update is a host-side O(1) dict /
deque operation outside any jitted device step (same contract as the
metrics registry), sampling is decided ONCE at admission, and an
unsampled request's spans are recorded only if they end up tail
exemplars — the bench measures a sampling=0 rerun against the traced
run and records both.

In-process simulations run many nodes in ONE process sharing this
module-global ``TRACER`` (like ``observability.METRICS``); spans carry
the recording node's name and collection dedupes by span id, so the
sim's cluster trace equals the shared recorder instead of multiplying
by the node count.
"""

from __future__ import annotations

import contextvars
import hashlib
import itertools
import secrets
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .observability import METRICS

# ----------------------------------------------------------------------
# span-name registry (lint-enforced: dmllint rule drift-span-names)
# ----------------------------------------------------------------------

#: the root span of a request's trace: admission -> terminal
SPAN_ROOT = "request"

#: Every name ``start_span(...)`` may emit, and therefore every stage
#: the attribution table can report. tools/dmllint.py cross-checks all
#: ``start_span("<literal>", ...)`` call sites in the tree against
#: this tuple — add the name HERE first, or the build fails. Keep the
#: comment on each line: it is the one place the stage vocabulary is
#: documented.
# plain assignment (no annotation): dmllint's _module_const_strs reads
# top-level Assign nodes, and this tuple IS its machine contract
SPAN_NAMES = (
    "request",     # root: admission -> terminal on the router
    "admission",   # REQUEST_SUBMIT handling (sampling, SLO, shed check)
    "formation",   # admission -> batch dispatch (the queue wait)
    "dispatch",    # ingress_submit -> WORKER_TASK_REQUEST send
    "fetch",       # worker: store replica fetch + host decode
    "infer",       # worker: backend infer call (device forward)
    "prefill",     # prefill-role member: chunked prompt prefill
    "handoff",     # decode primary: prefill RPC + KV slab pull
    "decode",      # decode side of a disaggregated LM batch
    "put",         # worker: output write + replicated store PUT
    "store_put",   # replicated store PUT under a request's trace
    "store_get",   # replicated store GET under a request's trace
    "result",      # job completion -> REQUEST_DONE push
    "marker",      # zero-duration exemplar marker (note_exemplar)
)

#: span events that force always-on exemplar capture: any span ending
#: with one of these pins its whole trace in the recorder regardless
#: of the head sampling decision — these are the requests that explain
#: the tail, and a tail you sampled away cannot be attributed
EXEMPLAR_EVENTS: Tuple[str, ...] = (
    "deadline_miss", "shed", "requeue", "fallback",
)

_M_SPANS = METRICS.counter(
    "tracing_spans_total",
    "finished spans observed by the flight recorder, by sampled=")
_M_DROPPED = METRICS.counter(
    "tracing_spans_dropped_total",
    "sampled spans evicted from the flight-recorder ring")
_M_EXEMPLARS = METRICS.counter(
    "tracing_exemplars_total",
    "tail-exemplar span captures, by kind= (deadline_miss|shed|...)")


# ----------------------------------------------------------------------
# context + span
# ----------------------------------------------------------------------


@dataclass(frozen=True)
class TraceContext:
    """What propagates across a hop: which trace, under which parent
    span, and whether the head decision sampled it. The wire form is
    a three-key dict (``t``/``p``/``s``) small enough to ride every
    batch and prefill frame next to ``slo_class``; ``key`` optionally
    binds the context to its request's input file (``f``) so batch-
    level code can route per-request contexts without a side table."""

    trace_id: str
    span_id: str = ""
    sampled: bool = True
    key: str = ""

    def to_wire(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"t": self.trace_id, "p": self.span_id,
                             "s": 1 if self.sampled else 0}
        if self.key:
            d["f"] = self.key
        return d

    @staticmethod
    def from_wire(d: Any) -> Optional["TraceContext"]:
        """Tolerant decode: byzantine/garbled context degrades to 'no
        trace', never to a handler exception."""
        if not isinstance(d, dict) or not isinstance(d.get("t"), str):
            return None
        return TraceContext(
            trace_id=d["t"],
            span_id=str(d.get("p", "")),
            sampled=bool(d.get("s", 1)),
            key=str(d.get("f", "")),
        )


class Span:
    """One live span; finished (and recorded) exactly once via
    ``end()`` or the context-manager exit."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "node", "sampled",
        "t0", "t1", "labels", "events", "_tracer",
    )

    def __init__(
        self, tracer: "Tracer", name: str, trace_id: str,
        parent_id: str, node: str, sampled: bool,
        t0: Optional[float] = None,
        labels: Optional[Dict[str, Any]] = None,
        span_id: Optional[str] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id or tracer._new_span_id()
        self.parent_id = parent_id
        self.node = node
        self.sampled = sampled
        self.t0 = time.time() if t0 is None else float(t0)
        self.t1: Optional[float] = None
        self.labels = dict(labels) if labels else {}
        self.events: List[List[Any]] = []

    def ctx(self) -> TraceContext:
        """Context for children of THIS span."""
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    def event(self, name: str, ts: Optional[float] = None) -> None:
        self.events.append([name, round(time.time() if ts is None
                                        else ts, 6)])

    def label(self, **labels: Any) -> None:
        self.labels.update(labels)

    def end(self, t1: Optional[float] = None) -> None:
        if self.t1 is not None:
            return  # idempotent: error paths may double-close
        self.t1 = time.time() if t1 is None else float(t1)
        self._tracer._record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()

    @property
    def duration(self) -> float:
        return (self.t1 or time.time()) - self.t0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "tid": self.trace_id, "sid": self.span_id,
            "par": self.parent_id, "name": self.name, "node": self.node,
            "t0": round(self.t0, 6),
            "t1": round(self.t1 if self.t1 is not None else self.t0, 6),
        }
        if self.labels:
            d["lb"] = {k: v for k, v in self.labels.items()}
        if self.events:
            d["ev"] = [list(e) for e in self.events]
        return d


#: batch-scoped contexts for code that cannot thread them through its
#: call signature (store put/get under a worker's fetch, the LM group
#: backends' prefill/handoff/decode internals): the service sets this
#: around a batch's backend call; asyncio tasks and to_thread hops
#: inherit it via contextvars copy semantics
CURRENT_CTXS: "contextvars.ContextVar[Tuple[TraceContext, ...]]" = (
    contextvars.ContextVar("dml_tpu_trace_ctxs", default=())
)


def current_ctxs() -> Tuple[TraceContext, ...]:
    """The batch's propagated trace contexts, sampled ones only (the
    common gate ordinary span-recording sites want)."""
    return tuple(c for c in CURRENT_CTXS.get() if c.sampled)


def current_all_ctxs() -> Tuple[TraceContext, ...]:
    """Every propagated context, sampled or not — for the ALWAYS-ON
    exemplar paths (a handoff fallback on an unsampled request must
    still be captured; that is the whole point of exemplars)."""
    return tuple(CURRENT_CTXS.get())


# ----------------------------------------------------------------------
# the flight recorder
# ----------------------------------------------------------------------


class Tracer:
    """Process-wide span recorder: seeded head sampling, a bounded
    ring of finished sampled spans, and always-on slowest-K + tail
    exemplar capture. Thread-safe (backends finish spans on decode
    threads)."""

    def __init__(
        self,
        sample_rate: float = 0.1,
        seed: int = 0,
        span_budget: int = 4096,
        slow_k: int = 32,
        exemplar_traces: int = 256,
    ):
        self._lock = threading.Lock()
        self._salt = secrets.token_hex(3)
        self._span_counter = itertools.count(1)
        self._trace_counter = itertools.count(1)
        self.configure(
            sample_rate=sample_rate, seed=seed, span_budget=span_budget,
            slow_k=slow_k, exemplar_traces=exemplar_traces,
        )

    def configure(
        self,
        sample_rate: Optional[float] = None,
        seed: Optional[int] = None,
        span_budget: Optional[int] = None,
        slow_k: Optional[int] = None,
        exemplar_traces: Optional[int] = None,
    ) -> None:
        """(Re)configure knobs; omitted arguments keep their value.
        Changing ``span_budget`` re-bounds the ring, carrying over the
        newest spans that still fit."""
        with self._lock:
            if sample_rate is not None:
                self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
            if seed is not None:
                self.seed = int(seed)
            if span_budget is not None:
                self.span_budget = max(16, int(span_budget))
                old = list(getattr(self, "_ring", ()))
                self._ring: "deque[Dict[str, Any]]" = deque(
                    old[-self.span_budget:], maxlen=self.span_budget
                )
            if slow_k is not None:
                self.slow_k = max(1, int(slow_k))
                self._slow: List[Tuple[float, Dict[str, Any]]] = list(
                    getattr(self, "_slow", ())
                )[: self.slow_k]
            if exemplar_traces is not None:
                self.max_exemplar_traces = max(4, int(exemplar_traces))
                self._exemplars: "OrderedDict[str, List[Dict[str, Any]]]" \
                    = OrderedDict(getattr(self, "_exemplars", ()))
            if not hasattr(self, "dropped"):
                self.dropped = 0
                self.peak_spans = 0
                self.recorded = 0

    # -- identity + sampling ------------------------------------------

    def _new_span_id(self) -> str:
        return f"s{self._salt}{next(self._span_counter):x}"

    def new_trace_id(self) -> str:
        return f"t{self._salt}{next(self._trace_counter):x}"

    def head_sample(self, trace_id: str) -> bool:
        """Deterministic seeded head decision: the same (seed,
        trace_id) pair samples identically on every node and every
        run — the property the bench's replayed traces rely on."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        h = hashlib.blake2b(
            f"{self.seed}:{trace_id}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") < self.sample_rate * 2.0 ** 64

    # -- span lifecycle -----------------------------------------------

    def start_span(
        self,
        name: str,
        ctx: Optional[TraceContext] = None,
        *,
        trace_id: Optional[str] = None,
        parent_id: str = "",
        node: str = "",
        sampled: Optional[bool] = None,
        t0: Optional[float] = None,
        labels: Optional[Dict[str, Any]] = None,
        span_id: Optional[str] = None,
    ) -> Span:
        """Open a span. ``ctx`` supplies trace/parent/sampled in one
        argument (the propagated-hop form); the keyword triple is the
        root-creation form. ``span_id`` pins the id explicitly — the
        promoted router reconstructs an adopted request's ROOT under
        its relayed original id, so spans the dead leader recorded
        against it still resolve their parent (no orphans across a
        failover). Names MUST come from ``SPAN_NAMES`` — dmllint
        cross-checks every literal call site."""
        if ctx is not None:
            trace_id = ctx.trace_id
            parent_id = ctx.span_id
            if sampled is None:
                sampled = ctx.sampled
        if trace_id is None:
            trace_id = self.new_trace_id()
        return Span(
            self, name, trace_id, parent_id, node,
            self.head_sample(trace_id) if sampled is None else sampled,
            t0=t0, labels=labels, span_id=span_id,
        )

    def _record(self, span: Span) -> None:
        d = span.to_dict()
        exemplar_kinds = [
            e[0] for e in span.events if e[0] in EXEMPLAR_EVENTS
        ]
        with self._lock:
            self.recorded += 1
            _M_SPANS.inc(sampled="yes" if span.sampled else "no")
            if span.sampled:
                if len(self._ring) == self.span_budget:
                    self.dropped += 1
                    _M_DROPPED.inc()
                self._ring.append(d)
                self.peak_spans = max(self.peak_spans, len(self._ring))
            # always-on slowest-K request roots (head sampling must
            # not be able to hide the slowest requests in the fleet)
            if span.name == SPAN_ROOT:
                dur = d["t1"] - d["t0"]
                self._slow.append((dur, d))
                self._slow.sort(key=lambda x: -x[0])
                del self._slow[self.slow_k:]
            for kind in exemplar_kinds:
                _M_EXEMPLARS.inc(kind=kind)
            if exemplar_kinds:
                self._pin_trace_locked(span.trace_id, d)

    def _pin_trace_locked(self, trace_id: str, d: Dict[str, Any]) -> None:
        spans = self._exemplars.get(trace_id)
        if spans is None:
            spans = self._exemplars[trace_id] = []
            # retroactively pin what the ring already holds for this
            # trace: an exemplar's earlier spans must survive eviction
            spans.extend(
                s for s in self._ring if s["tid"] == trace_id
            )
            while len(self._exemplars) > self.max_exemplar_traces:
                self._exemplars.popitem(last=False)
        if all(s["sid"] != d["sid"] for s in spans):
            spans.append(d)

    def note_exemplar(self, ctx: Optional[TraceContext], kind: str,
                      node: str = "", labels: Optional[Dict[str, Any]]
                      = None) -> None:
        """Record a zero-duration exemplar marker for ``ctx``'s trace
        (kind must be in ``EXEMPLAR_EVENTS``): the requeue/shed call
        sites have no surrounding interval worth a timed span, but the
        trace must still be pinned and the event must still show in
        the tree."""
        if ctx is None:
            return
        t = time.time()
        s = Span(self, "marker", ctx.trace_id, ctx.span_id, node, True,
                 t0=t, labels=labels)
        s.event(kind, t)
        s.end(t)

    # -- collection ----------------------------------------------------

    def dump(
        self,
        trace_ids: Optional[Iterable[str]] = None,
        max_spans: Optional[int] = None,
        strip: bool = False,
    ) -> List[Dict[str, Any]]:
        """Finished spans this node holds: the ring, the slowest-K
        roots, and every pinned exemplar trace, deduped by span id,
        newest-last. ``trace_ids`` filters; ``max_spans`` keeps the
        NEWEST — except exemplar-trace spans, which survive the cut
        first (the recorder pinned them against ring eviction; a
        collection cap must not un-pin them, or a deadline miss early
        in a long run loses exactly the trace that explains it).
        ``strip`` drops labels/events (the datagram-degraded form)."""
        want = set(trace_ids) if trace_ids is not None else None
        with self._lock:
            rows = list(self._ring)
            rows.extend(d for _, d in self._slow)
            for spans in self._exemplars.values():
                rows.extend(spans)
            pinned_tids = set(self._exemplars)
        seen: set = set()
        out: List[Dict[str, Any]] = []
        for d in rows:
            if d["sid"] in seen:
                continue
            if want is not None and d["tid"] not in want:
                continue
            seen.add(d["sid"])
            out.append(d)
        out.sort(key=lambda d: (d["t0"], d["sid"]))
        if max_spans is not None and len(out) > max_spans:
            ex = [d for d in out if d["tid"] in pinned_tids]
            if len(ex) >= max_spans:
                out = ex[-max_spans:]
            else:
                rest = [d for d in out if d["tid"] not in pinned_tids]
                out = rest[-(max_spans - len(ex)):] + ex
                out.sort(key=lambda d: (d["t0"], d["sid"]))
        if strip:
            out = [
                {k: v for k, v in d.items() if k not in ("lb", "ev")}
                for d in out
            ]
        return out

    def exemplar_trace_ids(self, kind: Optional[str] = None) -> List[str]:
        """Pinned exemplar traces, oldest first. ``kind`` filters to
        traces holding at least one span with that event (the signal
        plane attaches the freshest ``deadline_miss`` exemplar to a
        deadline-burn alert, not merely a recent shed)."""
        with self._lock:
            if kind is None:
                return list(self._exemplars)
            return [
                tid for tid, spans in self._exemplars.items()
                if any(
                    e[0] == kind
                    for s in spans for e in s.get("ev", ())
                )
            ]

    def stats(self) -> Dict[str, Any]:
        """Flight-recorder accounting (the bench's budget verdict):
        the ring NEVER exceeds ``span_budget`` by construction;
        ``peak_spans`` records the high-water mark so the artifact can
        prove it."""
        with self._lock:
            return {
                "span_budget": self.span_budget,
                "spans": len(self._ring),
                "peak_spans": self.peak_spans,
                "dropped": self.dropped,
                "recorded": self.recorded,
                "slow_k": self.slow_k,
                "slow_held": len(self._slow),
                "exemplar_traces": len(self._exemplars),
                "sample_rate": self.sample_rate,
                "within_budget": self.peak_spans <= self.span_budget,
            }

    def reset(self) -> None:
        """Drop every recorded span + counters (tests/bench phases);
        configuration survives."""
        with self._lock:
            self._ring.clear()
            self._slow = []
            self._exemplars = OrderedDict()
            self.dropped = 0
            self.peak_spans = 0
            self.recorded = 0


#: the process-wide recorder every subsystem writes into
TRACER = Tracer()


# ----------------------------------------------------------------------
# assembly + attribution + export
# ----------------------------------------------------------------------


def merge_span_dumps(
    dumps: Sequence[Sequence[Dict[str, Any]]],
) -> List[Dict[str, Any]]:
    """Fold per-node dumps into one deduped span list (in-process sims
    share one recorder, so every node returns the same spans — span
    ids make the dedupe exact; real deployments dedupe nothing)."""
    seen: set = set()
    out: List[Dict[str, Any]] = []
    for dump in dumps:
        for d in dump:
            sid = d.get("sid")
            if not isinstance(sid, str) or sid in seen:
                continue
            seen.add(sid)
            out.append(d)
    out.sort(key=lambda d: (d.get("t0", 0.0), d.get("sid", "")))
    return out


def assemble_traces(
    spans: Sequence[Dict[str, Any]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Group a span list by trace id, each trace's spans in start
    order (the stitched cross-node tree; parents sort before their
    children because a child starts after its parent)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for d in spans:
        tid = d.get("tid")
        if isinstance(tid, str):
            out.setdefault(tid, []).append(d)
    for rows in out.values():
        rows.sort(key=lambda d: (d.get("t0", 0.0), d.get("sid", "")))
    return out


def trace_covers(spans: Sequence[Dict[str, Any]],
                 stages: Sequence[str]) -> bool:
    """Whether one trace's spans include every named stage (the
    acceptance contract for the stitched disaggregated-path trace)."""
    have = {d.get("name") for d in spans}
    return all(s in have for s in stages)


def stage_breakdown(spans: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """Per-stage seconds for ONE trace: wall duration summed by span
    name, root span excluded (it IS the e2e). Batch-shared spans (a
    worker's fetch covers every request in the batch) count their full
    duration — the request waited that long regardless of who shared
    the ride — and nested detail spans (store_put under fetch) are
    reported under their own name, so stages are not disjoint by
    construction; the attribution table reads the top-level stage
    names."""
    out: Dict[str, float] = {}
    for d in spans:
        name = d.get("name")
        if name == SPAN_ROOT or not isinstance(name, str):
            continue
        dur = max(0.0, float(d.get("t1", 0.0)) - float(d.get("t0", 0.0)))
        out[name] = out.get(name, 0.0) + dur
    return out


def trace_e2e(spans: Sequence[Dict[str, Any]]) -> Optional[float]:
    """Root-span duration of one trace, if the root was recorded."""
    for d in spans:
        if d.get("name") == SPAN_ROOT:
            return max(0.0, float(d.get("t1", 0.0)) - float(d.get("t0", 0.0)))
    return None


def chrome_trace(spans: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome ``chrome://tracing`` / Perfetto JSON: one complete
    ('X') event per span — pid = recording node, tid = trace — plus an
    instant ('i') event per span event. Times in microseconds as the
    format demands."""
    nodes = sorted({str(d.get("node", "")) for d in spans})
    pid_of = {n: i + 1 for i, n in enumerate(nodes)}
    tids = sorted({str(d.get("tid", "")) for d in spans})
    tid_of = {t: i + 1 for i, t in enumerate(tids)}
    events: List[Dict[str, Any]] = []
    for n, pid in pid_of.items():
        events.append({
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": n or "?"},
        })
    for d in spans:
        pid = pid_of[str(d.get("node", ""))]
        tid = tid_of[str(d.get("tid", ""))]
        t0 = float(d.get("t0", 0.0))
        t1 = float(d.get("t1", t0))
        args: Dict[str, Any] = {
            "trace_id": d.get("tid"), "span_id": d.get("sid"),
            "parent": d.get("par"),
        }
        args.update(d.get("lb") or {})
        events.append({
            "ph": "X", "name": str(d.get("name", "?")), "cat": "dml",
            "pid": pid, "tid": tid,
            "ts": round(t0 * 1e6, 1),
            "dur": round(max(0.0, t1 - t0) * 1e6, 1),
            "args": args,
        })
        for ev in d.get("ev") or ():
            try:
                ev_name, ev_ts = str(ev[0]), float(ev[1])
            except (TypeError, ValueError, IndexError):
                continue
            events.append({
                "ph": "i", "name": ev_name, "cat": "dml", "s": "t",
                "pid": pid, "tid": tid, "ts": round(ev_ts * 1e6, 1),
            })
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def cohort_attribution(
    breakdowns: Sequence[Dict[str, float]],
    e2es: Sequence[float],
) -> Dict[str, Any]:
    """Mean per-stage seconds over a cohort of traces (the p99 cohort
    in the bench), plus how much of the cohort's mean e2e the named
    stages explain (``attributed_fraction`` — the >= 0.9 claim gate).
    Overlapping stages (store detail under fetch; pipelined decode
    under handoff) are EXCLUDED from the coverage sum via their known
    parents, so the fraction cannot exceed honesty by double
    counting."""
    if not breakdowns or not e2es:
        return {"n": 0}
    stages: Dict[str, float] = {}
    for b in breakdowns:
        for k, v in b.items():
            stages[k] = stages.get(k, 0.0) + v
    n = len(breakdowns)
    mean_stages = {k: v / n for k, v in sorted(stages.items())}
    mean_e2e = sum(e2es) / len(e2es)
    # top-level stages only: detail spans nest under (or run
    # concurrently with) these and would double-count the same wall
    # time — admission sits inside formation, store_* inside
    # fetch/put, and the disagg prefill/handoff/decode trio runs
    # INSIDE the primary's infer span (that is the point of the
    # disaggregation: it all overlaps the batch's device window)
    detail = {"store_put", "store_get", "admission", "decode",
              "prefill", "handoff", "marker"}
    covered = sum(v for k, v in mean_stages.items() if k not in detail)
    return {
        "n": n,
        "mean_e2e_ms": round(mean_e2e * 1e3, 2),
        "stage_ms": {k: round(v * 1e3, 2) for k, v in mean_stages.items()},
        "attributed_ms": round(covered * 1e3, 2),
        "attributed_fraction": (
            round(covered / mean_e2e, 4) if mean_e2e > 0 else None
        ),
    }
