"""ML job pipeline: intake, batching, fair-share scheduling, execution.

TPU-native rebuild of the reference's L7 (worker.py:176-495, 518-537,
887-1026) — see `cost_model` (analytical model + fair split),
`scheduler` (pure-logic coordinator state machine), and `service`
(the Node-attached I/O wiring).
"""

from .cost_model import ModelCost, batch_exec_time, query_rate, fair_split
from .scheduler import Batch, JobState, Scheduler
from .service import JobService

__all__ = [
    "ModelCost",
    "batch_exec_time",
    "query_rate",
    "fair_split",
    "Batch",
    "JobState",
    "Scheduler",
    "JobService",
]
