"""ML job pipeline: intake, batching, fair-share scheduling, execution.

TPU-native rebuild of the reference's L7 (worker.py:176-495, 518-537,
887-1026) — see `cost_model` (analytical model + fair split),
`scheduler` (pure-logic coordinator state machine), `service` (the
Node-attached I/O wiring), and `groups` (tensor-parallel worker
groups: a set of nodes pooling chips into one dp×tp scheduler slot).
"""

from .cost_model import (
    ModelCost, batch_exec_time, fair_split, fair_split_weighted,
    query_rate,
)
from .groups import GroupDegraded, GroupDirectory
from .scheduler import Batch, JobState, Scheduler
from .service import JobService

__all__ = [
    "ModelCost",
    "batch_exec_time",
    "query_rate",
    "fair_split",
    "fair_split_weighted",
    "Batch",
    "JobState",
    "Scheduler",
    "JobService",
    "GroupDegraded",
    "GroupDirectory",
]
