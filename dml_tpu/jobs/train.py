"""TrainJob: elastic data-parallel training as a first-class cluster
workload (ROADMAP item 3).

The cluster's scheduler so far moved inference batches only; the
`parallel/` package had the dp/tp step machinery, atomic checkpoints,
and a data loader but ran single-node, outside the cluster. This
module closes the gap: a **TrainJob** is a leader-coordinated training
run whose every *global step* is one scheduler job — `world` batches
of `shard_batch` input files each, fanned across the worker pool by
the same fair-share machinery that serves inference (SLO class
``train``, weight below ``batch``, so interactive p99 stays protected
while the trainer soaks idle slots).

The replicated store is both substrates at once:

- **dataset substrate** — each step's shard files are ordinary store
  objects the executing workers fetch over the data plane (replica
  fallback, version pinning, the works);
- **checkpoint substrate** — the coordinator PUTs a versioned
  checkpoint blob (`train_ckpt_<run>`) through the atomic PUT path,
  so a promoted leader adopts unfinished runs from the store exactly
  like `restore-jobs` adopts queues.

Step-exact accounting: the leader keeps a **monotone step ledger**;
a step is applied exactly once, in order. Duplicate completions (a
replayed ACK, a shadow job double-completed across a failover) are
*refused* by the ledger — the training analog of the batch-completion
dedup in `_h_task_ack`. The gradient math is deterministic (each
shard file's gradient is derived from its sdfs name), so
`replay_reference` can recompute the final parameter state from the
ledger history alone; the chaos invariant sweep uses that as its
no-step-lost / no-step-double-applied oracle.

Elasticity (MLPerf TPU-pod scaling, arxiv 1909.09756: reshape as a
first-class operation): at every step boundary the coordinator
compares the live worker pool and universe epoch against the run's
current world size. A change (join, graceful LEAVE, failure) triggers
checkpoint → restore → re-shard: the next step is dispatched at the
new world size with the learning rate rescaled linearly to the new
effective global batch (arxiv 1711.04325), and the reason is recorded
both in the ledger history and the `train_resharding_total{reason=}`
counter.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..observability import METRICS
from .cost_model import ModelCost

log = logging.getLogger("dml_tpu.jobs.train")

# The trainer is registered as a model like any other servable: the
# scheduler, relay, requeue, and completion-dedup paths need nothing
# new to move its batches.
TRAIN_MODEL = "cluster-trainer"
TRAIN_SLO_CLASS = "train"
TRAIN_CKPT_PREFIX = "train_ckpt_"
TRAIN_GRAD_DIM = 4
# requester-string tag: survives the submit relay, so a coordinator
# promoted mid-step can still attribute the shadow job's completion
# to (run, step, world, lr) without any new wire type
_REQ_TAG = "train:"

_M_STEPS = METRICS.counter(
    "train_steps_total", "global training steps applied exactly once"
)
_M_RESHARD = METRICS.counter(
    "train_resharding_total",
    "checkpoint-restore re-shards of a training run, per reason= "
    "(join / leave / failure / adopt)",
)
_M_STEP_WALL = METRICS.histogram(
    "train_step_wall_seconds",
    "dispatch-to-applied wall time of one global training step",
)
_M_EFF_BATCH = METRICS.gauge(
    "train_effective_batch",
    "current effective global batch (shard_batch x world) per run=",
)


# ----------------------------------------------------------------------
# spec + deterministic training math
# ----------------------------------------------------------------------


@dataclass
class TrainJobSpec:
    """Everything a run needs, serializable into the checkpoint blob
    so an adopting coordinator reconstructs the run bit-for-bit."""

    name: str
    dataset: List[str]  # sdfs names of the sharded input files
    steps: int = 16
    shard_batch: int = 2  # files per dp shard per step (fixed)
    base_lr: float = 0.1  # LR at world == base_world
    base_world: int = 1
    grad_dim: int = TRAIN_GRAD_DIM
    seed: int = 0
    checkpoint_every: int = 5  # periodic checkpoint cadence (steps)
    # floor on per-step wall (coordinator paces dispatch): chaos runs
    # use it so a run reliably spans the event schedule
    min_step_s: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "dataset": list(self.dataset),
            "steps": self.steps, "shard_batch": self.shard_batch,
            "base_lr": self.base_lr, "base_world": self.base_world,
            "grad_dim": self.grad_dim, "seed": self.seed,
            "checkpoint_every": self.checkpoint_every,
            "min_step_s": self.min_step_s,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainJobSpec":
        return cls(
            name=str(d["name"]), dataset=[str(f) for f in d["dataset"]],
            steps=int(d["steps"]), shard_batch=int(d["shard_batch"]),
            base_lr=float(d["base_lr"]),
            base_world=int(d.get("base_world", 1)),
            grad_dim=int(d.get("grad_dim", TRAIN_GRAD_DIM)),
            seed=int(d.get("seed", 0)),
            checkpoint_every=int(d.get("checkpoint_every", 5)),
            min_step_s=float(d.get("min_step_s", 0.0)),
        )


def lr_for(spec: TrainJobSpec, world: int) -> float:
    """Linear LR scaling with the effective global batch
    (arxiv 1711.04325): per-shard batch is fixed, so scaling is by
    world size relative to the spec's base world."""
    return spec.base_lr * (max(1, world) / max(1, spec.base_world))


def shard_files(spec: TrainJobSpec, step: int, world: int) -> List[str]:
    """The step's global batch: ``shard_batch * world`` dataset files,
    drawn deterministically from (spec.seed, step) via a per-step
    shuffled permutation cycle — every re-dispatch, shadow replay, and
    `replay_reference` pass sees the identical ordered list."""
    import random

    n = len(spec.dataset)
    if n == 0:
        raise ValueError(f"train run {spec.name}: empty dataset")
    need = spec.shard_batch * max(1, world)
    rng = random.Random((spec.seed * 1_000_003 + step) & 0x7FFFFFFF)
    perm = list(range(n))
    rng.shuffle(perm)
    return [spec.dataset[perm[i % n]] for i in range(need)]


def grad_for(sdfs_name: str, dim: int = TRAIN_GRAD_DIM) -> List[float]:
    """Deterministic per-file gradient, derived from the sdfs name's
    sha256 — the property the exactly-once oracle rests on: any node,
    any time, recomputes the same vector."""
    h = hashlib.sha256(sdfs_name.encode()).digest()
    return [
        int.from_bytes(h[4 * i: 4 * i + 4], "big") / 2.0**31 - 1.0
        for i in range(dim)
    ]


def apply_step(
    state: List[float], files: List[str], lr: float,
    dim: int = TRAIN_GRAD_DIM,
) -> List[float]:
    """SGD on the toy objective: subtract lr * mean(per-file grads),
    in the files' listed order — fixed op order means bitwise-equal
    floats between the live run and `replay_reference`."""
    acc = [0.0] * dim
    for f in files:
        g = grad_for(f, dim)
        for j in range(dim):
            acc[j] += g[j]
    n = max(1, len(files))
    return [state[j] - lr * (acc[j] / n) for j in range(dim)]


def replay_reference(
    spec: TrainJobSpec, history: List[Dict[str, Any]]
) -> List[float]:
    """Recompute the final parameter state from the ledger history
    alone. Equality with the live run's state proves every recorded
    step was applied exactly once with the recorded (world, lr)."""
    state = [0.0] * spec.grad_dim
    for e in history:
        files = shard_files(spec, int(e["step"]), int(e["world"]))
        state = apply_step(state, files, float(e["lr"]), spec.grad_dim)
    return state


def recover_sdfs_name(local_path: str) -> str:
    """Invert the worker's fetch-cache naming. Both fetch paths'
    version suffixes are handled (replica pre-fetch names the local
    copy ``name_versionN``, the data-plane download ``name.vN`` —
    service.py's to_sdfs re-key comment). Train file names carry no
    '/', so the replace in those schemes is a no-op for them."""
    base = os.path.basename(local_path)
    return re.sub(r"(\.v|_version)(\d+|latest)$", "", base)


def train_backend(
    dim: int = TRAIN_GRAD_DIM, per_file_s: float = 0.02
) -> Any:
    """The worker-side shard executor, registered as an ordinary
    inference backend: computes each fetched file's gradient and
    returns it as that file's inline result. Deterministic, jax-free
    (the cluster machinery is what's under test), with a real per-file
    cost so data-parallel speedup is measurable end-to-end."""

    async def backend(model: str, paths: List[str]):
        t0 = time.monotonic()
        await asyncio.sleep(per_file_s * max(1, len(paths)))
        results = {
            p: grad_for(recover_sdfs_name(p), dim) for p in paths
        }
        return results, time.monotonic() - t0, None

    return backend


# ----------------------------------------------------------------------
# step ledger
# ----------------------------------------------------------------------


class StepLedger:
    """Monotone exactly-once accounting for global steps. ``applied``
    is the count of applied steps (== the next expected step id);
    `record` accepts only that step, `refuse` counts everything else —
    duplicates from replayed ACKs / shadow double-completions, and
    out-of-order completions racing an adoption from an older
    checkpoint."""

    def __init__(self) -> None:
        self.applied = 0
        self.history: List[Dict[str, Any]] = []
        self.duplicates_refused = 0
        self.out_of_order_refused = 0

    def next_step(self) -> int:
        return self.applied

    def record(self, step: int, world: int, lr: float, reason: str) -> None:
        if step != self.applied:
            raise ValueError(
                f"ledger: step {step} is not next (applied={self.applied})"
            )
        self.history.append(
            {"step": step, "world": world, "lr": lr, "reason": reason}
        )
        self.applied += 1

    def refuse(self, step: int) -> str:
        """Classify + count a non-next completion. Returns the kind."""
        if step < self.applied:
            self.duplicates_refused += 1
            return "duplicate"
        self.out_of_order_refused += 1
        return "out_of_order"

    def snapshot(self) -> Dict[str, Any]:
        return {
            "applied": self.applied,
            "history": [dict(e) for e in self.history],
            "duplicates_refused": self.duplicates_refused,
            "out_of_order_refused": self.out_of_order_refused,
        }

    @classmethod
    def restore(cls, d: Dict[str, Any]) -> "StepLedger":
        led = cls()
        led.applied = int(d["applied"])
        led.history = [dict(e) for e in d.get("history", [])]
        led.duplicates_refused = int(d.get("duplicates_refused", 0))
        led.out_of_order_refused = int(d.get("out_of_order_refused", 0))
        if len(led.history) != led.applied:
            raise ValueError(
                f"ledger restore: applied={led.applied} but "
                f"history has {len(led.history)} entries"
            )
        return led


@dataclass
class TrainRun:
    """Coordinator-side state of one training run."""

    spec: TrainJobSpec
    state: List[float]
    ledger: StepLedger
    world: int
    lr: float
    done: bool = False
    # in-flight step job dispatched by THIS coordinator incarnation
    # (an adopted run starts with none; a shadow job completing for it
    # is attributed via the requester tag instead)
    inflight_job: Optional[int] = None
    dispatch_t0: float = 0.0
    epoch_seen: int = -1  # universe epoch at the last dispatch
    resharding: Dict[str, int] = field(default_factory=dict)
    grad_mismatches: int = 0
    redispatches: int = 0
    ckpt_puts: int = 0
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    done_event: asyncio.Event = field(default_factory=asyncio.Event)

    def effective_batch(self) -> int:
        return self.spec.shard_batch * self.world

    def status(self) -> Dict[str, Any]:
        return {
            "name": self.spec.name,
            "done": self.done,
            "applied": self.ledger.applied,
            "steps": self.spec.steps,
            "world": self.world,
            "lr": self.lr,
            "effective_batch": self.effective_batch(),
            "resharding": dict(self.resharding),
            "duplicates_refused": self.ledger.duplicates_refused,
            "out_of_order_refused": self.ledger.out_of_order_refused,
            "grad_mismatches": self.grad_mismatches,
            "redispatches": self.redispatches,
            "ckpt_puts": self.ckpt_puts,
        }


# ----------------------------------------------------------------------
# coordinator
# ----------------------------------------------------------------------


class TrainCoordinator:
    """Leader-resident driver for training runs, attached to the
    JobService like the signal plane and the autoscaler: constructed
    on every node (so the trainer backend is registered everywhere —
    restarts and joiners can execute shards immediately), but it
    *drives* runs only while this node leads. Adoption of unfinished
    runs after a failover happens from the store's checkpoint blobs,
    scanned by the tick loop."""

    def __init__(self, node: Any, jobs: Any) -> None:
        self.node = node
        self.jobs = jobs
        self.runs: Dict[str, TrainRun] = {}
        self._tick_task: Optional[asyncio.Task] = None
        self._last_scan = 0.0
        jobs.register_lm(
            TRAIN_MODEL,
            backend=train_backend(),
            cost=ModelCost(
                load_time=0.0, first_query=0.02, per_query=0.02,
                batch_size=4,
            ),
            patterns=("train_shard_*",),
        )
        # fair-share weight for the train class: below batch (1.0),
        # far below interactive (3.0) — the trainer soaks idle slots
        jobs.scheduler.class_weights[TRAIN_SLO_CLASS] = float(
            getattr(node.spec, "train_class_weight", 0.5)
        )
        jobs.on_job_done_cbs.append(self._on_job_done)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._tick_task is None:
            self._tick_task = asyncio.create_task(
                self._tick_loop(), name=f"{self._me}-train-tick"
            )

    async def stop(self) -> None:
        from ..cluster.util import reap_task

        t = self._tick_task
        self._tick_task = None
        await reap_task(t, self.node.me, "train tick loop")

    @property
    def _me(self) -> str:
        return self.node.me.unique_name

    # -- run intake -----------------------------------------------------

    async def start_run(self, spec: TrainJobSpec) -> TrainRun:
        """Begin a run on the current coordinator. Checkpoints the
        step-0 state BEFORE the first dispatch so a leader lost at any
        point afterward leaves an adoptable blob in the store."""
        if not self.node.is_leader:
            raise RuntimeError("start_run runs on the coordinator")
        if spec.name in self.runs:
            raise ValueError(f"train run {spec.name} already exists")
        world = self._pool_world()
        run = TrainRun(
            spec=spec,
            state=[0.0] * spec.grad_dim,
            ledger=StepLedger(),
            world=world,
            lr=lr_for(spec, world),
        )
        run.epoch_seen = int(getattr(self.node.spec, "universe_epoch", 0))
        self.runs[spec.name] = run
        await self._checkpoint(run)
        async with run.lock:
            await self._dispatch(run)
        log.info(
            "%s: train run %s started (steps=%d world=%d lr=%.4g)",
            self._me, spec.name, spec.steps, world, run.lr,
        )
        return run

    async def wait(self, name: str, timeout: float = 60.0) -> Dict[str, Any]:
        """Await a run's completion (coordinator-local)."""
        run = self.runs[name]
        await asyncio.wait_for(run.done_event.wait(), timeout)
        return run.status()

    def status(self) -> Dict[str, Any]:
        return {name: r.status() for name, r in self.runs.items()}

    # -- the step engine ------------------------------------------------

    def _pool_world(self) -> int:
        return max(1, len(self.jobs.worker_pool()))

    async def _dispatch(self, run: TrainRun) -> None:
        """Dispatch the next global step as one scheduler job. Called
        with run.lock held. The step boundary is also the re-shard
        point: a pool/universe change since the last dispatch
        checkpoint-restores the run onto the new world first."""
        if run.done or run.inflight_job is not None:
            return
        spec = run.spec
        epoch = int(getattr(self.node.spec, "universe_epoch", 0))
        world = self._pool_world()
        if world != run.world:
            reason = self._reshard_reason(run, world, epoch)
            await self._reshard(run, world, epoch, reason)
        run.epoch_seen = epoch
        step = run.ledger.next_step()
        files = shard_files(spec, step, run.world)
        job_id = self.jobs.scheduler.next_job_id()
        requester = (
            f"{_REQ_TAG}{spec.name}:{step}:{run.world}:{run.lr!r}"
        )
        replicas = {
            f: self.jobs.store.metadata.replicas_of(f)
            for f in set(files)
        }
        self.jobs.scheduler.submit_job(
            job_id, TRAIN_MODEL, files, len(files), requester, replicas,
            batch_size=spec.shard_batch, inline_results=True,
            slo_class=TRAIN_SLO_CLASS,
        )
        self.jobs._relay_submit(
            job_id,
            {"job": job_id, "model": TRAIN_MODEL, "n": len(files),
             "files": list(files), "batch_size": spec.shard_batch,
             "requester": requester, "gen": self.jobs._relay_gen,
             "inline": True, "slo": TRAIN_SLO_CLASS},
        )
        run.inflight_job = job_id
        run.dispatch_t0 = time.monotonic()
        _M_EFF_BATCH.set(run.effective_batch(), run=spec.name)
        self.jobs._run_schedule()

    def _reshard_reason(
        self, run: TrainRun, new_world: int, epoch: int
    ) -> str:
        if new_world > run.world:
            return "join"
        # shrink: a graceful LEAVE bumps the universe epoch; a crash
        # shrinks the live pool without touching the universe
        return "leave" if epoch != run.epoch_seen else "failure"

    async def _reshard(
        self, run: TrainRun, new_world: int, epoch: int, reason: str
    ) -> None:
        """Checkpoint-restore re-shard at a step boundary: persist the
        current state, read it back through the store path (the same
        bytes an adopting coordinator would see), and come back up at
        the new world size with the LR rescaled to the new effective
        global batch."""
        spec = run.spec
        await self._checkpoint(run)
        blob = await self.jobs.store.get_bytes(
            TRAIN_CKPT_PREFIX + spec.name
        )
        d = json.loads(blob.decode())
        run.state = [float(x) for x in d["state"]]
        run.ledger = StepLedger.restore(d["ledger"])
        run.world = new_world
        run.lr = lr_for(spec, new_world)
        run.epoch_seen = epoch
        run.resharding[reason] = run.resharding.get(reason, 0) + 1
        _M_RESHARD.inc(reason=reason)
        _M_EFF_BATCH.set(run.effective_batch(), run=spec.name)
        log.info(
            "%s: train run %s re-sharded (%s) -> world=%d lr=%.4g "
            "at step %d", self._me, spec.name, reason, new_world,
            run.lr, run.ledger.next_step(),
        )

    async def _checkpoint(self, run: TrainRun) -> None:
        blob = json.dumps({
            "v": 1,
            "spec": run.spec.to_dict(),
            "state": run.state,
            "ledger": run.ledger.snapshot(),
            "world": run.world,
            "lr": run.lr,
            "done": run.done,
        }).encode()
        await self.jobs.store.put_bytes(
            TRAIN_CKPT_PREFIX + run.spec.name, blob
        )
        run.ckpt_puts += 1

    # -- completion (the exactly-once seam) -----------------------------

    def _on_job_done(self, st: Any, worker: Optional[str]) -> None:
        """Job-terminal observer (sync, must not block): attribute the
        job to (run, step, world, lr) via the requester tag and hand
        off to the async applier."""
        req = getattr(st, "requester", "") or ""
        if not isinstance(req, str) or not req.startswith(_REQ_TAG):
            return
        try:
            name, step_s, world_s, lr_s = req[len(_REQ_TAG):].rsplit(
                ":", 3
            )
            step, world, lr = int(step_s), int(world_s), float(lr_s)
        except ValueError:
            log.warning("%s: unparseable train requester %r", self._me, req)
            return
        self.jobs._spawn_bg(
            self._complete(st, name, step, world, lr),
            f"train-complete-{name}-{step}",
        )

    async def _complete(
        self, st: Any, name: str, step: int, world: int, lr: float
    ) -> None:
        run = self.runs.get(name)
        if run is None or run.done:
            return
        async with run.lock:
            if run.done:
                return
            spec = run.spec
            if getattr(st, "error", None):
                # the step job failed (batch retry cap under chaos):
                # the ledger did not advance, so re-dispatching the
                # same step is safe — and the boundary re-shards first
                # if the failure changed the pool
                if run.inflight_job == st.job_id:
                    run.inflight_job = None
                run.redispatches += 1
                log.info(
                    "%s: train run %s step %d job %d failed (%s); "
                    "re-dispatching", self._me, name, step, st.job_id,
                    st.error,
                )
                await self._dispatch(run)
                return
            if step != run.ledger.next_step():
                kind = run.ledger.refuse(step)
                log.info(
                    "%s: train run %s refused %s completion of step %d "
                    "(next=%d)", self._me, name, kind, step,
                    run.ledger.next_step(),
                )
                if run.inflight_job == st.job_id:
                    run.inflight_job = None
                    await self._dispatch(run)
                return
            files = shard_files(spec, step, world)
            # cross-check the workers' ACK-carried gradients against
            # the deterministic reference before applying it — the
            # applied math is the reference (identical by
            # construction), so a mismatch is execution evidence
            # drift, not a training divergence
            inline = getattr(st, "inline_results", None) or {}
            for f in set(files):
                got = inline.get(f)
                if got is not None and [float(x) for x in got] != \
                        grad_for(f, spec.grad_dim):
                    run.grad_mismatches += 1
            run.state = apply_step(run.state, files, lr, spec.grad_dim)
            reason = "steady" if step else "start"
            run.ledger.record(step, world, lr, reason)
            _M_STEPS.inc(run=name)
            if run.inflight_job == st.job_id:
                wall = time.monotonic() - run.dispatch_t0
                _M_STEP_WALL.observe(wall)
                if spec.min_step_s > 0 and wall < spec.min_step_s:
                    await asyncio.sleep(spec.min_step_s - wall)
            run.inflight_job = None
            if run.ledger.applied >= spec.steps:
                run.done = True
                await self._checkpoint(run)
                run.done_event.set()
                log.info(
                    "%s: train run %s complete (%d steps, final "
                    "world=%d)", self._me, name, spec.steps, run.world,
                )
                return
            if spec.checkpoint_every > 0 and \
                    run.ledger.applied % spec.checkpoint_every == 0:
                await self._checkpoint(run)
            await self._dispatch(run)

    # -- tick loop: adoption + stall recovery ---------------------------

    async def _tick_loop(self) -> None:
        interval = 0.25
        while True:
            await asyncio.sleep(interval)
            if not self.node.is_leader:
                continue
            try:
                now = time.monotonic()
                if now - self._last_scan >= 1.0:
                    self._last_scan = now
                    await self._adopt_scan()
                for run in list(self.runs.values()):
                    await self._unstall(run)
            except Exception:
                log.exception("%s: train tick failed", self._me)

    async def _adopt_scan(self) -> None:
        """Adopt unfinished runs this coordinator doesn't know — the
        failover path. The restored monotone ledger absorbs any shadow
        job still in flight from the previous leader: whichever side
        completes a step first advances it, the other is refused, and
        deterministic gradients make either apply identical."""
        try:
            listing = await self.jobs.store.ls_all(
                TRAIN_CKPT_PREFIX + "*"
            )
        except Exception:
            return
        for sdfs_name in sorted(listing):
            name = sdfs_name[len(TRAIN_CKPT_PREFIX):]
            if not name or name in self.runs:
                continue
            try:
                blob = await self.jobs.store.get_bytes(sdfs_name)
                d = json.loads(blob.decode())
                spec = TrainJobSpec.from_dict(d["spec"])
                run = TrainRun(
                    spec=spec,
                    state=[float(x) for x in d["state"]],
                    ledger=StepLedger.restore(d["ledger"]),
                    world=int(d["world"]),
                    lr=float(d["lr"]),
                    done=bool(d.get("done")),
                )
                run.epoch_seen = int(
                    getattr(self.node.spec, "universe_epoch", 0)
                )
                if name in self.runs:
                    # start_run registered it while the blob fetch
                    # was in flight; the live run wins
                    continue
                self.runs[name] = run
                if run.done:
                    run.done_event.set()
                    continue
                run.resharding["adopt"] = (
                    run.resharding.get("adopt", 0) + 1
                )
                _M_RESHARD.inc(reason="adopt")
                log.info(
                    "%s: adopted train run %s at step %d/%d",
                    self._me, name, run.ledger.applied, spec.steps,
                )
                async with run.lock:
                    await self._dispatch(run)
            except Exception:
                log.exception(
                    "%s: failed to adopt train run %s", self._me, name
                )

    async def _unstall(self, run: TrainRun) -> None:
        """Stall recovery: an active run must always have a step in
        flight. Covers a dispatched job lost to a scheduler snapshot
        restore, and the idle gap right after adoption."""
        if run.done:
            return
        if run.inflight_job is not None and \
                self.jobs.scheduler.jobs.get(run.inflight_job) is None:
            # the coordinator no longer tracks the job (restored
            # snapshot predates it); the ledger makes re-dispatch safe
            run.inflight_job = None
            run.redispatches += 1
        if run.inflight_job is None and not run.lock.locked():
            async with run.lock:
                await self._dispatch(run)
