"""Analytical cost model + fair-share VM split.

The reference predicts a batch's wall time on a worker VM as

    T(B) = download*B + load + first + per_image*(B-1)

(models.py:128-139) with constants measured once on CPU and hardcoded
(worker.py:57-89). Its scheduler then picks the VM split between the
two active models that minimizes the *relative difference of their
query rates* (worker.py:303-324).

The TPU cost structure differs in two ways, so the model is a
parameterized dataclass rather than baked constants:

- both models stay resident in HBM, so `load` is paid once per worker
  lifetime, not per batch; the steady-state per-batch time is
  `download*B + first_amortized + per_query*B` where `first` only
  matters right after a batch-size change (recompile);
- `per_query` on TPU is the batch step time / B measured by the
  engine at warmup (engine.cost_constants), typically two orders of
  magnitude below the reference's 250-325 ms/image CPU numbers.

The split search itself is the reference's exact semantics: enumerate
all (i, j) with i+j == n_workers, i,j >= 1, pick the argmin of
|rate_a - rate_b| / max(rate_a, rate_b).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence, Tuple


@dataclass(frozen=True)
class ModelCost:
    """Per-model scheduling constants (reference ModelParameters,
    models.py:128-139). `resident=True` is the TPU regime: weights
    stay in HBM so load time is excluded from steady-state batches."""

    load_time: float
    first_query: float
    per_query: float
    download_time: float = 0.05
    batch_size: int = 32
    resident: bool = True

    def with_measurements(
        self,
        load_time: Optional[float] = None,
        first_query: Optional[float] = None,
        per_query: Optional[float] = None,
        batch_size: Optional[int] = None,
    ) -> "ModelCost":
        """Fold in engine warmup measurements (the reference hardcodes
        its constants; we re-measure on the real device)."""
        kw = {}
        if load_time is not None:
            kw["load_time"] = load_time
        if first_query is not None:
            kw["first_query"] = first_query
        if per_query is not None:
            kw["per_query"] = per_query
        if batch_size is not None:
            kw["batch_size"] = batch_size
        return replace(self, **kw)


def batch_exec_time(cost: ModelCost, batch: Optional[int] = None) -> float:
    """Predicted wall time of one batch on one worker.

    Reference formula (models.py:138-139): dl*B + load + first + per*(B-1).
    TPU steady state drops the per-batch `load` and folds `first` into
    compile-time only; one batched XLA program costs per_query*B.
    """
    b = batch if batch is not None else cost.batch_size
    if b <= 0:
        return 0.0
    if cost.resident:
        return cost.download_time * b + cost.per_query * b
    return cost.download_time * b + cost.load_time + cost.first_query + cost.per_query * (b - 1)


def query_rate(
    cost: ModelCost, n_workers: float, batch: Optional[int] = None
) -> float:
    """Predicted queries/sec with `n_workers` VMs running this model
    (reference: rate = vms * batch_size / exec_time, worker.py:303-324).

    `n_workers` may be a float: a tensor-parallel worker GROUP
    (jobs/groups.py) counts as one pool slot with capacity = its
    measured/estimated throughput multiple of a single chip, so the
    fair split sees aggregate rate, not slot count."""
    b = batch if batch is not None else cost.batch_size
    t = batch_exec_time(cost, b)
    if t <= 0 or n_workers <= 0:
        return 0.0
    return n_workers * b / t


def overlap_headroom(
    fetch_s: float, decode_s: float, infer_s: float, put_s: float
) -> float:
    """Analytic upper bound on the depth-2 worker-pipelining speedup
    given measured per-batch stage walls.

    Depth-2 staging overlaps batch N+1's prepare (store fetch + host
    decode) with batch N's in-flight inference; the PUT and residue
    stay serial. Perfect overlap takes the serial wall
    ``prep + infer + put`` to ``max(prep, infer) + put``, so the bound
    is their ratio — ≤ (prep+infer)/max(prep,infer) ≤ 2. A bound near
    1.0 predicts the overlap state machine cannot pay for itself (the
    r5 regime: fast link, prep ≪ infer); the DepthController's probe
    is the measurement this prior is checked against, never a
    substitute for it.
    """
    prep = max(fetch_s + decode_s, 0.0)
    infer = max(infer_s, 0.0)
    put = max(put_s, 0.0)
    serial = prep + infer + put
    overlapped = max(prep, infer) + put
    if overlapped <= 0.0 or serial <= 0.0:
        return 1.0
    return round(serial / overlapped, 3)


def fair_split(
    n_workers: int, cost_a: ModelCost, cost_b: ModelCost
) -> Tuple[int, int]:
    """Split `n_workers` between two active models to minimize the
    relative difference of their predicted query rates (the reference's
    dual-model case, worker.py:303-324: enumerate every split, argmin
    |r_a - r_b| / max). Each model gets at least one worker when
    n_workers >= 2."""
    return fair_split_weighted([1.0] * max(0, n_workers), cost_a, cost_b)


def fair_split_weighted(
    weights: Sequence[float], cost_a: ModelCost, cost_b: ModelCost
) -> Tuple[int, int]:
    """`fair_split` over a pool whose slots have unequal capacity.

    A tensor-parallel worker group (jobs/groups.py) occupies ONE pool
    slot but serves with the aggregate throughput of its members, so
    each slot carries a weight (single chip = 1.0, a formed group =
    its capacity). The enumeration is the reference's exact shape —
    every contiguous split of the pool, argmin of the relative rate
    difference — run over the pool sorted by weight DESCENDING and
    scored with weighted rates, with both assignment directions tried
    (the heavy group going to model A or to model B are different
    splits). Uniform weights reduce this to the reference's
    `fair_split` bit-for-bit.

    Returns (count_for_a, count_for_b); with heterogeneous weights the
    counts mean "model a takes that many of the heaviest slots" when
    the directed form says so — schedulers that place work should use
    `fair_split_weighted_directed`, which also returns WHICH model the
    heavy prefix belongs to, and grow that model heaviest-slot-first.
    """
    i, j, _ = fair_split_weighted_directed(weights, cost_a, cost_b)
    return (i, j)


def class_split(
    n_slots: int,
    cost: ModelCost,
    weight_a: float,
    weight_b: float,
) -> Tuple[int, int]:
    """Split `n_slots` free workers between TWO SLO classes of one
    model in proportion to their weights, through the SAME fair-split
    enumeration the dual-model scheduler uses: each class presents the
    model's cost with its exec time scaled BY its weight. Since
    ``query_rate ∝ capacity / exec``, equalizing the scaled rates
    allocates capacity ∝ weight — interactive at weight 3 vs batch at
    1 converges to a 3:1 slot share, with fair_split's granularity
    handling (each class gets at least one slot when n >= 2) for
    free."""
    if n_slots <= 0:
        return (0, 0)
    wa = max(float(weight_a), 1e-9)
    wb = max(float(weight_b), 1e-9)

    def scaled(w: float) -> ModelCost:
        return replace(
            cost,
            first_query=cost.first_query * w,
            per_query=cost.per_query * w,
            download_time=cost.download_time * w,
            load_time=cost.load_time * w,
        )

    return fair_split_weighted(
        [1.0] * n_slots, scaled(wa), scaled(wb)
    )


def fair_split_weighted_directed(
    weights: Sequence[float], cost_a: ModelCost, cost_b: ModelCost
) -> Tuple[int, int, bool]:
    """`fair_split_weighted` plus the placement direction: returns
    ``(count_for_a, count_for_b, a_heavy)`` where ``a_heavy`` means
    model a's count refers to the HEAVIEST slots of the pool (else
    model b's does). Counts alone can't carry that — (1, 3) over
    weights [2,1,1,1] is balanced only if the 1 IS the weight-2 slot —
    so the caller must assign the heavy-side model its workers in
    descending-weight order."""
    n = len(weights)
    if n <= 0:
        return (0, 0, True)
    if n == 1:
        # single slot: give it to the slower model (higher per-query
        # time) so the worst-case rate is maximized
        return (
            (1, 0, True)
            if batch_exec_time(cost_a) >= batch_exec_time(cost_b)
            else (0, 1, False)
        )
    w = sorted((float(x) for x in weights), reverse=True)
    prefix = [0.0]
    for x in w:
        prefix.append(prefix[-1] + x)
    best = (1, n - 1, True)
    best_score = float("inf")
    # two passes, reference order first: with uniform weights every
    # pass-2 candidate duplicates a pass-1 capacity pair, so the
    # strict-< replacement keeps pass 1's (= the reference's) winner
    # including its tie-breaking order
    for a_heavy in (True, False):
        for i in range(1, n):
            j = n - i
            heavy, light = prefix[i], prefix[n] - prefix[i]
            cap_a, cap_b, split = (
                (heavy, light, (i, j, True)) if a_heavy
                else (light, heavy, (j, i, False))
            )
            ra = query_rate(cost_a, cap_a)
            rb = query_rate(cost_b, cap_b)
            hi = max(ra, rb)
            score = abs(ra - rb) / hi if hi > 0 else 0.0
            if score < best_score:
                best_score = score
                best = split
    return best
