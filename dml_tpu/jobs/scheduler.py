"""Pure-logic coordinator state machine: intake, batching, fair-share
assignment, preemption, failure requeue, metrics.

This is the reference's scheduling core (worker.py:176-495 intake +
schedule_job; worker.py:989-1026 ACK bookkeeping; worker.py:1279-1306
failure requeue) extracted into a deterministic, I/O-free class so the
edge cases (preempt/requeue/failover) are unit-testable — SURVEY §7
"hard parts" #3 calls this out as the reason the reference's state
machine was only ever hand-tested.

The service layer (service.py) owns all sockets and devices; it feeds
events in and performs the returned `Assignment`s.

Semantics preserved from the reference:
- wrap-around sampling: a job of N queries cycles the image list until
  N inputs are scheduled (preprocess_job_request, worker.py:188-245)
- one outstanding batch per worker (workers_tasks_dict, worker.py:54)
- single active model -> every free worker takes from its queue
  (worker.py:257-300)
- two active models -> fair split by predicted query rate, growing
  each side to its share and preempting the other's workers; preempted
  batches return to the FRONT of their queue (worker.py:303-480)
- worker death -> its in-flight batch returns to queue front
  (worker.py:1279-1306)
- job completion when every batch has been ACKed (worker.py:1018-1019)

Deliberate non-copies (intent over accident, SURVEY §7):
- batches are padded/short-tail tolerant: the tail batch keeps its
  natural length and the engine pads to the compiled shape, so no
  recompile (the reference emits ragged tails, worker.py:229-237)
- job ids are a monotonic counter from 1, not seeded at 30
  (worker.py:47)
"""

from __future__ import annotations

import statistics
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from ..observability import METRICS
from ..tracing import TRACER, TraceContext
from .cost_model import (
    ModelCost,
    class_split,
    fair_split_weighted_directed,
    query_rate,
)

# Coordinator metrics: the registry form of the reference's C1/C2
# console (see observability.py's C1-C5 map). The exact-sample
# c1_stats/c2_stats read-outs below stay for reference parity; these
# are the mergeable cluster-wide equivalents METRICS_PULL aggregates.
_M_QUERIES = METRICS.counter(
    "jobs_queries_total", "queries completed, per model (C1 count)")
_M_RATE = METRICS.gauge(
    "jobs_query_rate_per_s",
    "trailing 10s per-model query rate, refreshed per batch ACK (C1)")
_M_QUERY_LAT = METRICS.histogram(
    "jobs_query_latency_seconds",
    "per-query processing time, per model (C2: mean + percentiles)")
_M_BATCH_EXEC = METRICS.histogram(
    "jobs_batch_exec_seconds", "per-batch worker exec wall, per model")
_M_QUEUE_DEPTH = METRICS.gauge(
    "jobs_queue_depth", "queued batches, per model")
_M_WORKERS_BUSY = METRICS.gauge(
    "jobs_workers_busy", "workers with a batch in flight (C5 size)")
_M_PREEMPTIONS = METRICS.counter(
    "jobs_preemptions_total",
    "batches displaced by the dual-model fair split")
_M_REQUEUES = METRICS.counter(
    "jobs_requeues_total",
    "batches returned to a queue front (worker death + live failure)")
_M_JOBS_DONE = METRICS.counter(
    "jobs_completed_total", "jobs fully completed, per model")
_M_JOBS_FAILED = METRICS.counter(
    "jobs_failed_total", "jobs retired with an error, per model")
_M_DEPTH = METRICS.gauge(
    "jobs_pipeline_depth",
    "worker-pipelining depth currently in force on the coordinator")
_M_PROBE_QPS = METRICS.histogram(
    "jobs_depth_probe_qps",
    "measured ACK throughput of each depth-probe phase, by depth")
_M_PROBES = METRICS.counter(
    "jobs_depth_probes_total",
    "depth probe cycles committed, by trigger (warmup|drift|ttl|pool)")
_M_PROBE_ABORTS = METRICS.counter(
    "jobs_depth_probe_aborts_total",
    "probe cycles abandoned (work drained / phase timed out)")


class DepthController:
    """Probe-and-commit controller for ``Scheduler.pipeline_depth``.

    Round 5's artifact of record measured static depth-2 pipelining as
    a pessimization (0.91×/0.85× vs the depth-1 serial loop) while r4's
    congested-link captures had it winning 1.47–1.57× — like the
    sync-vs-pipelined dispatch choice, the winner is decided by link
    weather, not by the code. This applies the same cure the engine's
    ``choose_dispatch_mode`` proved on the C4 path: measure both modes
    on real work, commit to the winner, and re-measure when conditions
    drift (Orca/vLLM's measured-not-assumed scheduling discipline).

    Pure logic, deterministic under an injected clock: the service
    feeds it the coordinator's batch-ACK stream and applies the depth
    it returns. Until a probe commits, the depth is 1 — the
    reference-faithful cheap sync path (the mode that was NEVER the
    r5 pessimization) — so short jobs that don't accumulate enough
    backlog to probe serve safely rather than inheriting overlap on
    faith. One probe cycle runs two phases — ``probe_batches``
    counted ACKs at depth 1, then at depth 2 — and each phase
    discards the FIRST ACK from every worker it hears (that worker's
    in-flight batch may have executed under the previous depth; one
    global transition discard is not enough on a multi-worker pool),
    with the phase clock starting at the last discard before counting
    begins. Commit prefers depth 1 unless depth 2's measured rate
    wins by more than ``noise_margin`` (overlap must pay for its
    state machine).

    After commit the controller watches the trailing per-stage walls
    (fetch / infer / put — the same ACK-carried timings
    ``breakdown_stats`` aggregates) against the probe-time signature;
    a stage mean drifting past ``drift_ratio`` in either direction
    re-arms the probe, so congested links regain overlap and healed
    links fall back to the cheap path automatically. ``reprobe_ttl_s``
    re-arms on age alone (link weather drifts without a stage-wall
    signature move when it shifts all stages together).
    """

    PHASES = (1, 2)

    def __init__(
        self,
        probe_batches: int = 5,
        noise_margin: float = 0.05,
        drift_ratio: float = 1.75,
        min_probe_backlog: Optional[int] = None,
        reprobe_ttl_s: float = 600.0,
        probe_phase_timeout_s: float = 60.0,
        initial_depth: int = 1,
        now: Callable[[], float] = time.time,
    ):
        self.probe_batches = max(2, int(probe_batches))
        self.noise_margin = float(noise_margin)
        self.drift_ratio = float(drift_ratio)
        # a probe needs enough queued work to feed BOTH phases plus
        # their transition batches, or phase rates measure starvation
        self.min_probe_backlog = (
            int(min_probe_backlog) if min_probe_backlog is not None
            else 2 * (self.probe_batches + 1)
        )
        self.reprobe_ttl_s = float(reprobe_ttl_s)
        self.probe_phase_timeout_s = float(probe_phase_timeout_s)
        self.now = now
        self.depth = int(initial_depth)
        # warmup: waiting for enough backlog to probe; probing: a
        # phase is collecting ACKs; settled: committed, watching drift
        self.state = "warmup"
        self.probes = 0
        self.reprobes = 0
        self.aborted_probes = 0
        self.committed_at: Optional[float] = None
        self.signature: Optional[Dict[str, float]] = None
        self.last_probe: Optional[Dict[str, Any]] = None
        self._trigger = "warmup"
        self._phase = 0
        self._phase_t0: Optional[float] = None
        # wall time the phase BEGAN (not its first ACK): the phase
        # timeout must fire even when zero ACKs ever arrive (workers
        # died right after the probe started), or the controller
        # wedges in 'probing' forever — TTL only covers 'settled'
        self._phase_wall0: float = 0.0
        # last probing ACK seen (counted OR discarded): the timeout
        # means "ACKs stopped", so it measures from the last sign of
        # life — a slow-but-flowing congested phase (exactly where
        # depth 2 wins) must not abort mid-measurement
        self._phase_last_ack: float = 0.0
        # abort cooldown: an aborted probe must NOT restart in the
        # same tick (a stalled pool with standing backlog would cycle
        # probe/abort forever, flapping the depth each timeout)
        self._no_probe_before: float = 0.0
        # worker -> first-ACK-of-this-phase discard pending (their
        # in-flight batch may predate the depth switch)
        self._phase_skip_seen: Dict[str, bool] = {}
        # pool size the committed depth was measured against (None
        # until first observed): elastic membership can grow or shrink
        # the slot count mid-job, which changes the overlap economics
        # as surely as link weather does — a size change re-arms the
        # probe (trigger "pool") so the committed depth is re-validated
        # against the pool that actually exists now
        self._pool_size: Optional[int] = None
        self._phase_images = 0
        self._phase_acks = 0
        self._phase_rates: Dict[int, float] = {}
        self._probe_stage_sum = {"fetch": 0.0, "infer": 0.0, "put": 0.0}
        self._probe_stage_n = 0
        self._trail: Deque[Tuple[float, float, float]] = deque(
            maxlen=2 * self.probe_batches
        )
        _M_DEPTH.set(self.depth)

    # -- scheduling-round hook ----------------------------------------

    def tick(self, queued_batches: int) -> int:
        """Called once per scheduling round with the current backlog;
        returns the depth the scheduler should run this round."""
        t = self.now()
        if (
            self.state == "settled"
            and self.reprobe_ttl_s > 0
            and self.committed_at is not None
            and t - self.committed_at >= self.reprobe_ttl_s
        ):
            self._rearm("ttl")
        if self.state == "probing":
            # a phase whose ACK stream STOPPED — including one that
            # never received any (workers died right after the probe
            # started) — must not pin a half-measured depth forever:
            # abandon, keep the last commit's winner. Measured from
            # the last ACK, not the first: a slow-but-flowing
            # congested phase is a measurement, not a stall.
            ref = max(self._phase_wall0, self._phase_last_ack)
            if t - ref > self.probe_phase_timeout_s:
                self._abort_probe()
        if (
            self.state == "warmup"
            and queued_batches >= self.min_probe_backlog
            and t >= self._no_probe_before
        ):
            self._begin_probe()
        return self.depth

    # -- pool-size hook (elastic membership) --------------------------

    def on_pool_size(self, n_slots: int) -> None:
        """Called per scheduling round with the slot count. A change
        counts as DRIFT: a settled commit re-arms (a join/leave that
        changed the pool mid-job invalidates the probe's premise —
        more slots deepen the fetch/put overlap window, fewer starve
        it), and an in-flight probe aborts (its two phases would be
        measuring different pools). The first observation only
        records the size — bring-up is not drift."""
        if self._pool_size is None:
            self._pool_size = int(n_slots)
            return
        if int(n_slots) == self._pool_size:
            return
        self._pool_size = int(n_slots)
        if self.state == "settled":
            self.reprobes += 1
            self._rearm("pool")
        elif self.state == "probing":
            self._abort_probe()

    # -- ACK hook -----------------------------------------------------

    def on_ack(
        self,
        n_images: int,
        fetch: float = 0.0,
        infer: float = 0.0,
        put: float = 0.0,
        worker: str = "",
    ) -> int:
        """Fold one worker batch-ACK into the controller; returns the
        depth to apply from here on. `worker` identifies the ACK's
        sender so each phase can discard every worker's transition
        batch (one global discard under-counts on a multi-worker
        pool: W in-flight batches may predate the depth switch)."""
        t = self.now()
        if self.state == "probing":
            self._phase_last_ack = t
            if not self._phase_skip_seen.get(worker):
                # this worker's first ACK of the phase: its batch may
                # have executed under the previous depth — discard.
                # The phase clock starts at the LAST discard before
                # counting begins (clean work starts after the
                # stragglers drain)
                self._phase_skip_seen[worker] = True
                if self._phase_acks == 0:
                    self._phase_t0 = t
                return self.depth
            if self._phase_t0 is None:  # defensive; discards above
                self._phase_t0 = t      # always set it first
                return self.depth
            self._phase_acks += 1
            self._phase_images += int(n_images)
            self._probe_stage_sum["fetch"] += fetch
            self._probe_stage_sum["infer"] += infer
            self._probe_stage_sum["put"] += put
            self._probe_stage_n += 1
            if self._phase_acks >= self.probe_batches:
                wall = max(t - self._phase_t0, 1e-9)
                rate = self._phase_images / wall
                self._phase_rates[self.depth] = rate
                _M_PROBE_QPS.observe(rate, depth=str(self.depth))
                if self._phase + 1 < len(self.PHASES):
                    self._phase += 1
                    self.depth = self.PHASES[self._phase]
                    self._phase_t0 = None
                    self._phase_wall0 = t
                    self._phase_skip_seen = {}
                    self._phase_images = 0
                    self._phase_acks = 0
                    _M_DEPTH.set(self.depth)
                else:
                    self._commit(t)
        elif self.state == "settled" and self.signature is not None:
            self._trail.append((fetch, infer, put))
            if len(self._trail) == self._trail.maxlen and self._drifted():
                self.reprobes += 1
                self._rearm("drift")
        return self.depth

    # -- internals ----------------------------------------------------

    def _rearm(self, trigger: str) -> None:
        self.state = "warmup"
        self._trigger = trigger
        self._trail.clear()

    def _begin_probe(self) -> None:
        self.state = "probing"
        self._phase = 0
        self.depth = self.PHASES[0]
        self._phase_t0 = None
        self._phase_wall0 = self.now()
        self._phase_last_ack = 0.0
        self._phase_skip_seen = {}
        self._phase_images = 0
        self._phase_acks = 0
        self._phase_rates = {}
        self._probe_stage_sum = {"fetch": 0.0, "infer": 0.0, "put": 0.0}
        self._probe_stage_n = 0
        _M_DEPTH.set(self.depth)

    def _abort_probe(self) -> None:
        self.aborted_probes += 1
        _M_PROBE_ABORTS.inc()
        # fall back to what the last commit decided (or the cheap
        # serial path when nothing ever committed) and re-arm — but
        # with a cooldown: without it a stalled pool with standing
        # backlog re-begins the probe in the SAME tick and cycles
        # probe/abort (depth flapping) every timeout period
        win = self.last_probe["winner"] if self.last_probe else 1
        self.depth = win
        self._no_probe_before = self.now() + self.probe_phase_timeout_s
        self._rearm(self._trigger)
        _M_DEPTH.set(self.depth)

    def _commit(self, t: float) -> None:
        r1 = self._phase_rates.get(1, 0.0)
        r2 = self._phase_rates.get(2, 0.0)
        ratio = (r2 / r1) if r1 > 0 else float("inf")
        win = 2 if ratio > 1.0 + self.noise_margin else 1
        self.depth = win
        self.state = "settled"
        self.committed_at = t
        n = max(self._probe_stage_n, 1)
        self.signature = {
            k: v / n for k, v in self._probe_stage_sum.items()
        }
        self._trail.clear()
        self.probes += 1
        if win == 2:
            reason = (
                f"depth-2 overlap won the probe ({ratio:.2f}x > "
                f"1+{self.noise_margin:g} noise margin)"
            )
        else:
            reason = (
                f"depth-1: overlap did not pay ({ratio:.2f}x <= "
                f"1+{self.noise_margin:g} noise margin) — cheap sync "
                "path wins on this link"
            )
        self.last_probe = {
            "qps_depth1": round(r1, 2),
            "qps_depth2": round(r2, 2),
            "ratio_d2_vs_d1": round(ratio, 3) if r1 > 0 else None,
            "winner": win,
            "trigger": self._trigger,
            "reason": reason,
        }
        _M_PROBES.inc(trigger=self._trigger)
        _M_DEPTH.set(win)

    def _drifted(self) -> bool:
        """Trailing stage-wall means vs the probe-time signature; sub-
        millisecond walls are floored so idle-stage jitter (a 0.1 ms
        put doubling to 0.2 ms) can't thrash the probe."""
        assert self.signature is not None
        n = len(self._trail)
        floor = 1e-3
        for i, k in enumerate(("fetch", "infer", "put")):
            cur = max(sum(s[i] for s in self._trail) / n, floor)
            ref = max(self.signature.get(k, 0.0), floor)
            r = cur / ref
            if r > self.drift_ratio or r < 1.0 / self.drift_ratio:
                return True
        return False

    def explain(self) -> Dict[str, Any]:
        """Operator surface (CLI `breakdown`): the committed depth AND
        why — probe rates, trigger, drift signature."""
        trail = None
        if self._trail:
            n = len(self._trail)
            trail = {
                k: round(sum(s[i] for s in self._trail) / n, 6)
                for i, k in enumerate(("fetch", "infer", "put"))
            }
        return {
            "state": self.state,
            "depth": self.depth,
            "probes": self.probes,
            "reprobes": self.reprobes,
            "aborted_probes": self.aborted_probes,
            "probe_batches": self.probe_batches,
            "min_probe_backlog": self.min_probe_backlog,
            "noise_margin": self.noise_margin,
            "drift_ratio": self.drift_ratio,
            "last_probe": self.last_probe,
            "pool_size": self._pool_size,
            "signature_s": (
                {k: round(v, 6) for k, v in self.signature.items()}
                if self.signature else None
            ),
            "trailing_s": trail,
        }


@dataclass
class Batch:
    """One unit of schedulable work (reference: a batch entry in the
    model's pending queue, worker.py:229-245)."""

    job_id: int
    batch_id: int
    model: str
    files: List[str]
    # file -> replica unique_names holding it (resolved at intake,
    # reference worker.py:290-297)
    replicas: Dict[str, List[str]] = field(default_factory=dict)
    # file -> version pinned at assignment time, so a re-PUT during the
    # job can't make workers serve mixed generations of an input
    versions: Dict[str, int] = field(default_factory=dict)
    # times a live worker reported failure for this batch (deterministic
    # failures must eventually fail the JOB, not requeue forever)
    failures: int = 0
    # session-affinity target (request front door, dml_tpu/ingress/):
    # the worker that holds this batch's sessions' KV state from their
    # previous turns. BEST-EFFORT — the single-model assignment pass
    # gives the batch to this worker when it is free, and any free
    # worker otherwise; a dead or busy target never strands the batch.
    affinity: Optional[str] = None
    # token-streaming routing for ingress LM batches: input file ->
    # LIST of [client unique_name, request id] targets (several
    # requests may share one input). The executing worker exposes one
    # stream PER REQUEST on its data plane and notifies each client
    # (REQUEST_STREAM_READY) before decode begins.
    streams: Dict[str, List[Any]] = field(default_factory=dict)
    # ingress batches carry results INLINE in the batch ACK (when they
    # fit a datagram) instead of a replicated-store PUT + GET round
    # trip per batch: per-request serving cannot afford 3x-replicated
    # store objects per formed batch, and nothing ever get-output's an
    # ingress job. Oversized results fall back to the store path.
    inline_results: bool = False
    # SLO class of the requests this batch formed from (ingress;
    # formed batches are single-class by construction). None =
    # operator-submitted work. Classes sharing one model queue get
    # WEIGHTED fair shares of its free workers (`class_weights` /
    # `_take_batches`) instead of one FIFO.
    slo_class: Optional[str] = None
    # per-request trace contexts (dml_tpu/tracing.py wire dicts, one
    # per request, keyed to its input file via "f"): ride next to
    # slo_class through intake -> relay -> WORKER_TASK_REQUEST so the
    # executing worker's fetch/infer/put spans land in each request's
    # cross-node trace. Empty for operator jobs.
    traces: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def key(self) -> Tuple[int, int]:
        return (self.job_id, self.batch_id)

    def trace_ctxs(self) -> List[TraceContext]:
        """Decoded SAMPLED contexts (the gate every instrumentation
        site wants); garbled entries drop silently."""
        out = []
        for e in self.traces:
            c = TraceContext.from_wire(e)
            if c is not None and c.sampled:
                out.append(c)
        return out


@dataclass
class JobState:
    """Coordinator-side bookkeeping for one submitted job (reference
    job_reqester_dict, worker.py:242-245)."""

    job_id: int
    model: str
    requester: str
    total_queries: int
    pending_batches: int
    done: bool = False
    error: Optional[str] = None  # set when the job FAILED (batch cap)
    # batch ids already counted done — guards double-decrement when a
    # falsely-suspected worker's ACK races the reassigned copy's ACK
    completed_batches: set = field(default_factory=set)
    # ACK-carried results of inline-results (ingress) batches, merged
    # across the job's batches; transient — NOT snapshotted (a
    # restored job's batches re-execute and re-deliver)
    inline_results: Optional[Dict[str, Any]] = None
    # last batch ACK's carried stage walls (fetch/backend/infer/put/
    # exec seconds): the router's per-request terminal attribution
    # source. Transient like inline_results.
    stage_timing: Optional[Dict[str, float]] = None


@dataclass
class Assignment:
    """An action for the service to perform: send this batch to this
    worker. `preempted` carries the batch that was displaced (already
    requeued at the front of its model's queue)."""

    worker: str
    batch: Batch
    preempted: Optional[Batch] = None
    # staged=True: a PIPELINE assignment — the worker should fetch and
    # decode this batch now but dispatch it only after its current
    # batch's inference completes (depth-2 worker pipelining)
    staged: bool = False


class Scheduler:
    """Deterministic scheduler state. All methods are synchronous and
    side-effect-free beyond their own state; time is injectable."""

    def __init__(
        self,
        costs: Optional[Dict[str, ModelCost]] = None,
        now: Callable[[], float] = time.time,
    ):
        self.costs: Dict[str, ModelCost] = dict(costs or {})
        self.now = now
        self.queues: Dict[str, Deque[Batch]] = {}
        self.in_progress: Dict[str, Batch] = {}  # worker -> batch
        # Worker pipelining (depth 2): with pipeline_depth > 1 the
        # single-model scheduler STAGES one extra batch per busy worker
        # so the worker overlaps batch N+1's store-fetch + host JPEG
        # decode + device dispatch with batch N's in-flight inference.
        # Default 1 preserves the reference's one-outstanding-batch-
        # per-worker rule (workers_tasks_dict, worker.py:54) exactly;
        # the service turns it up for serving. Dual-model rounds never
        # stage (fair-share preemption and staging interact badly:
        # a staged batch would instantly widen the preempting model's
        # footprint beyond its computed share).
        self.pipeline_depth = 1
        # per-slot capacity from the last schedule() call (worker ->
        # weight; absent = 1.0). Group primaries carry their group's
        # aggregate capacity here (jobs/groups.py).
        self.worker_weights: Dict[str, float] = {}
        self.prefetch: Dict[str, Batch] = {}  # worker -> staged batch
        self._revoked_stages: List[Tuple[str, Tuple[int, int]]] = []
        self.jobs: Dict[int, JobState] = {}  # in-flight only
        # finished jobs, bounded: serves late status queries + duplicate
        # ACKs without growing with coordinator lifetime
        self.done_jobs: Dict[int, JobState] = {}
        self.max_done_jobs = 1000
        # a batch failing this many times on LIVE workers fails its job
        # loudly instead of front-requeuing forever
        self.max_batch_failures = 5
        self._newly_failed: List[JobState] = []
        self._job_counter = 0
        # requeues observed (worker death + live-worker batch failure)
        # — the recovery evidence the failure-injection bench records
        self.requeue_count = 0
        # Per-class WEIGHTED fair share inside each model queue: when
        # batches of different SLO classes share a queue, free workers
        # split between the classes in weight proportion (class_split,
        # built on the dual-model fair_split_weighted enumeration)
        # instead of strict FIFO — sustained batch-class load can no
        # longer queue interactive requests behind its whole backlog.
        # Unknown/None classes weigh 1.0; set to {} to restore FIFO.
        self.class_weights: Dict[str, float] = {
            "interactive": 3.0, "batch": 1.0, "train": 0.5,
        }
        # model -> class -> batches granted (the cross-round deficit
        # memory that keeps single-slot rounds from starving the
        # light-weight class); reset when the model's queue drains
        self._class_served: Dict[str, Dict[Optional[str], int]] = {}
        # metrics (reference worker.py:485-495, 1000-1001); bounded
        # deques so a long-lived coordinator doesn't grow forever
        self.max_samples = 10_000
        self.query_counts: Dict[str, int] = {}
        # per model: (timestamp, exec_time_s, image_count)
        self.latency_samples: Dict[str, Deque[Tuple[float, float, int]]] = {}
        # per model: (timestamp, predicted_rate) per scheduling round
        self.rate_samples: Dict[str, Deque[Tuple[float, float]]] = {}
        # read-time C1 rate refresh: without this the gauge freezes at
        # its last batch-ACK value, so an idle coordinator would show
        # phantom traffic in every scrape/METRICS_PULL forever. Held
        # weakly by the registry — dies with this scheduler.
        METRICS.add_collector(self._refresh_rate_gauges)

    def reweight_classes(
        self, weights: Dict[str, float]
    ) -> Dict[str, float]:
        """Replace the per-class fair-share split — the autoscaler's
        capacity-reallocation actuation point. Weights must be positive
        and finite (a zero or NaN weight would silently starve a class
        forever, which is an outage, not a reallocation). The cross-
        round deficit memory resets so the new split takes effect from
        a clean slate instead of paying down debts accrued under the
        old one. Returns the previous map."""
        for k, v in weights.items():
            w = float(v)
            if not (w > 0.0) or w != w or w == float("inf"):
                raise ValueError(f"bad class weight {k}={v!r}")
        prev = dict(self.class_weights)
        self.class_weights = {k: float(v) for k, v in weights.items()}
        self._class_served.clear()
        return prev

    # ------------------------------------------------------------------
    # model config
    # ------------------------------------------------------------------

    def _refresh_gauges(self) -> None:
        """Queue-depth and busy-worker gauges (C5-size view); called
        wherever queues or in_progress change. O(active models)."""
        for m, q in self.queues.items():
            _M_QUEUE_DEPTH.set(len(q), model=m)
        _M_WORKERS_BUSY.set(len(self.in_progress))

    def _refresh_rate_gauges(self) -> None:
        """Trailing-10s C1 rate gauge, recomputed from the sample
        window NOW — runs on every batch ACK and (as a registry
        collector) before every exposition, so the gauge decays to
        zero on an idle coordinator exactly like the read-time
        c1_stats it mirrors. Bounded walk: newest-first, stops at the
        window edge."""
        t = self.now()
        for model, samples in self.latency_samples.items():
            recent = 0
            for ts, _, n in reversed(samples):
                if ts < t - 10.0:
                    break
                recent += n
            _M_RATE.set(recent / 10.0, model=model)

    def set_cost(self, model: str, cost: ModelCost) -> None:
        self.costs[model] = cost

    def set_batch_size(self, model: str, batch_size: int) -> None:
        """C3 verb (reference SET_BATCH_SIZE, worker.py:1028-1037):
        future jobs batch at the new size; queued batches are unchanged
        (matching the reference, which re-slices only new jobs)."""
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        cost = self.costs.get(model)
        if cost is None:
            raise KeyError(f"unknown model {model!r}")
        self.costs[model] = cost.with_measurements(batch_size=batch_size)

    def _queue(self, model: str) -> Deque[Batch]:
        return self.queues.setdefault(model, deque())

    # ------------------------------------------------------------------
    # intake (reference handle_job_request + preprocess_job_request,
    # worker.py:176-245)
    # ------------------------------------------------------------------

    def next_job_id(self) -> int:
        self._job_counter += 1
        return self._job_counter

    def observe_job_id(self, job_id: int) -> None:
        """Keep the counter ahead of ids minted elsewhere (standby
        replaying the primary's relays)."""
        self._job_counter = max(self._job_counter, job_id)

    def submit_job(
        self,
        job_id: int,
        model: str,
        files: Sequence[str],
        n_queries: int,
        requester: str,
        replicas: Optional[Dict[str, List[str]]] = None,
        batch_size: Optional[int] = None,
        affinity: Optional[str] = None,
        streams: Optional[Dict[str, List[Any]]] = None,
        inline_results: bool = False,
        slo_class: Optional[str] = None,
        traces: Optional[List[Dict[str, Any]]] = None,
    ) -> JobState:
        """Wrap-around sample `n_queries` inputs from `files`, slice
        into batches of the model's current batch size, queue them.

        `batch_size` pins the slicing explicitly — the standby replays
        the primary's relayed value so shadow batch ids always match
        even if a C3 fanout datagram was lost. `affinity`/`streams`/
        `traces` are ingress metadata (see Batch) carried on every
        batch; trace entries follow their request's input file into
        its slice."""
        if not files:
            raise ValueError("no input files to sample from")
        if n_queries <= 0:
            raise ValueError("n_queries must be positive")
        if batch_size is not None:
            bs = batch_size
        else:
            cost = self.costs.get(model)
            bs = cost.batch_size if cost else 32
        if bs <= 0:
            raise ValueError(f"batch_size must be positive, got {bs}")
        inputs = [files[i % len(files)] for i in range(n_queries)]
        batches: List[Batch] = []
        for b, start in enumerate(range(0, n_queries, bs)):
            chunk = inputs[start : start + bs]
            chunk_set = set(chunk)
            batches.append(
                Batch(
                    job_id=job_id,
                    batch_id=b,
                    model=model,
                    files=chunk,
                    replicas={
                        f: (replicas or {}).get(f, []) for f in chunk
                    },
                    affinity=affinity,
                    streams={
                        f: list(v) for f, v in (streams or {}).items()
                        if f in chunk
                    },
                    inline_results=inline_results,
                    slo_class=slo_class,
                    traces=[
                        dict(e) for e in (traces or [])
                        if isinstance(e, dict)
                        and e.get("f") in chunk_set
                    ],
                )
            )
        q = self._queue(model)
        q.extend(batches)
        st = JobState(
            job_id=job_id,
            model=model,
            requester=requester,
            total_queries=n_queries,
            pending_batches=len(batches),
        )
        self.jobs[job_id] = st
        self.observe_job_id(job_id)
        self._refresh_gauges()
        return st

    # ------------------------------------------------------------------
    # scheduling (reference schedule_job, worker.py:255-495)
    # ------------------------------------------------------------------

    def active_models(self) -> List[str]:
        """Models with queued work, in deterministic order."""
        return sorted(m for m, q in self.queues.items() if q)

    def schedule(
        self,
        workers: Sequence[str],
        weights: Optional[Dict[str, float]] = None,
    ) -> List[Assignment]:
        """Compute assignments for this round.

        `workers` is the current live worker pool (coordinator and
        standby excluded by the caller, mirroring the reference's
        H3..H10 set, worker.py:52). Returns the assignments to send;
        in-progress state is updated as if they were delivered.

        `weights` carries per-slot capacity for pool entries that are
        not single chips — a formed tensor-parallel worker group
        (jobs/groups.py) occupies one slot under its primary's name
        with weight = aggregate capacity. Omitted entries weigh 1.0.
        The fair split and the predicted-rate samples use the weights;
        assignment mechanics (one outstanding batch per slot, staging,
        preemption, requeue) are unchanged — a group is exactly one
        worker to them.
        """
        self.worker_weights = dict(weights or {})
        # staged (pipeline) batches drain their model's queue ahead of
        # execution; if a SECOND model's work shows up, un-stage them
        # so the fair split sees the full picture — otherwise the new
        # model waits behind work that hasn't even dispatched
        staged_models = {b.model for b in self.prefetch.values()}
        queued_models = {m for m, q in self.queues.items() if q}
        if self.prefetch and len(staged_models | queued_models) > 1:
            self._unstage_all()
        active = self.active_models()
        # drained models drop their class-deficit memory: a later mix
        # starts fresh instead of replaying an old imbalance as a burst
        for m in list(self._class_served):
            if m not in active:
                del self._class_served[m]
        if not active or not workers:
            return []
        workers = list(workers)
        if len(active) == 1:
            out = self._assign_free(active[0], workers)
        else:
            out = self._schedule_two(active[0], active[1], workers)
        self._record_rates(workers)
        self._refresh_gauges()
        return out

    def _unstage_all(self) -> None:
        """Return every staged batch to its queue front and record the
        revocation so the service can tell the workers (a worker whose
        stage survives here would dispatch it anyway; completion dedup
        makes that merely wasteful, not wrong)."""
        for w, b in list(self.prefetch.items()):
            self._queue(b.model).appendleft(b)
            self._revoked_stages.append((w, b.key))
        self.prefetch.clear()

    def pop_revoked_stages(self) -> List[Tuple[str, Tuple[int, int]]]:
        """(worker, batch key) stage revocations since the last call."""
        out, self._revoked_stages = self._revoked_stages, []
        return out

    def _free_workers(self, workers: Sequence[str]) -> List[str]:
        return [w for w in workers if w not in self.in_progress]

    def _take_batches(self, model: str, k: int) -> List[Batch]:
        """Pop up to `k` batches of `model` for this round — FIFO when
        the queue is single-class (or `class_weights` is empty),
        otherwise a WEIGHTED split of the k slots between the queued
        SLO classes:

        - two classes (the DEFAULT_CLASSES shape): `class_split`, the
          dual-model fair_split_weighted enumeration with each class
          presenting the model's cost scaled by its weight — slots
          land in weight proportion;
        - more: proportional stride over cumulative weighted grants.

        Slots a class cannot fill redistribute; a cross-round deficit
        memory (`_class_served`, reset when the queue drains) hands a
        zero-slot class its overdue slot, so k=1 rounds cannot starve
        the light class. FIFO order is preserved WITHIN each class —
        the split changes who goes next, never reorders a class's own
        work."""
        q = self._queue(model)
        n = min(k, len(q))
        if n <= 0:
            return []
        order: List[Optional[str]] = []
        per_class: Dict[Optional[str], int] = {}
        for b in q:
            if b.slo_class not in per_class:
                order.append(b.slo_class)
            per_class[b.slo_class] = per_class.get(b.slo_class, 0) + 1
        if not self.class_weights or len(order) == 1:
            return [q.popleft() for _ in range(n)]
        order.sort(key=str)  # deterministic, not arrival-dependent
        w = {
            c: max(float(self.class_weights.get(c or "", 1.0)), 1e-9)
            for c in order
        }
        served = self._class_served.setdefault(model, {})
        counts: Dict[Optional[str], int]
        if len(order) == 2:
            cost = self.costs.get(model, ModelCost(0, 0, 0.001))
            c1, c2 = order
            n1, n2 = class_split(n, cost, w[c1], w[c2])
            counts = {c1: n1, c2: n2}
        else:
            counts = {c: 0 for c in order}
            for _ in range(n):
                pick = min(order, key=lambda c: (
                    (served.get(c, 0) + counts[c]) / w[c], str(c)
                ))
                counts[pick] += 1
        # cap by availability, redistribute the leftovers
        spare = 0
        for c in order:
            if counts[c] > per_class[c]:
                spare += counts[c] - per_class[c]
                counts[c] = per_class[c]
        while spare > 0:
            grantable = [c for c in order if counts[c] < per_class[c]]
            if not grantable:
                break
            pick = min(grantable, key=lambda c: (
                (served.get(c, 0) + counts[c]) / w[c], str(c)
            ))
            counts[pick] += 1
            spare -= 1
        # deficit correction: a class with work but zero slots takes
        # one from the most-ahead donor once its weighted grant count
        # trails by a full slot (otherwise k=1 rounds always go to the
        # heavy class and the light one starves forever)
        for c in order:
            if counts[c] == 0 and per_class[c] > 0:
                donors = [d for d in order if counts[d] > 0]
                if not donors:
                    continue
                d = max(donors, key=lambda d: (
                    (served.get(d, 0) + counts[d] - 1) / w[d], str(d)
                ))
                if (served.get(c, 0) + 1) / w[c] <= (
                    served.get(d, 0) + counts[d] - 1
                ) / w[d] + 1e-9:
                    counts[d] -= 1
                    counts[c] += 1
        # single O(n) pass: partition the queue into granted batches
        # (per-class quota, FIFO within class) and the rebuilt
        # remainder — deque.remove per grant would rescan the whole
        # queue per slot, quadratic in exactly the deep-backlog
        # regime the class weighting exists for
        out: List[Batch] = []
        rest: List[Batch] = []
        taken = {c: 0 for c in order}
        want = sum(counts.values())
        for b in q:
            if (len(out) < want
                    and taken.get(b.slo_class, 0)
                    < counts.get(b.slo_class, 0)):
                out.append(b)
                taken[b.slo_class] = taken.get(b.slo_class, 0) + 1
            else:
                rest.append(b)
        q.clear()
        q.extend(rest)
        for b in out:
            served[b.slo_class] = served.get(b.slo_class, 0) + 1
        return out

    def _assign_free(self, model: str, workers: Sequence[str]) -> List[Assignment]:
        """Single-model case (worker.py:257-300): pour the queue onto
        every free worker. Batches carrying a session-affinity target
        (ingress) get a preference pass first: a batch whose affinity
        worker is FREE this round lands there (the node holding its
        sessions' KV state); everything else — including affinity
        batches whose target is busy or gone — pours in reference
        FIFO order. Affinity is a placement preference, never a
        gate: no batch waits for its target."""
        q = self._queue(model)
        out: List[Assignment] = []
        free = self._free_workers(workers)
        if any(b.affinity for b in q):
            free_set = set(free)
            # membership tested INSIDE the loop: two queued batches
            # sharing an affinity target must not both land on it —
            # the second assignment would silently overwrite the
            # first in in_progress and orphan that batch forever
            for batch in list(q):
                if batch.affinity and batch.affinity in free_set:
                    q.remove(batch)
                    self.in_progress[batch.affinity] = batch
                    out.append(
                        Assignment(worker=batch.affinity, batch=batch)
                    )
                    free_set.discard(batch.affinity)
            free = [w for w in free if w in free_set]
        for w, batch in zip(free, self._take_batches(model, len(free))):
            self.in_progress[w] = batch
            out.append(Assignment(worker=w, batch=batch))
        if self.pipeline_depth > 1:
            stageable = [
                w for w in workers
                if w in self.in_progress and w not in self.prefetch
            ]
            for w, batch in zip(
                stageable, self._take_batches(model, len(stageable))
            ):
                self.prefetch[w] = batch
                out.append(Assignment(worker=w, batch=batch, staged=True))
        return out

    def _schedule_two(
        self, model_a: str, model_b: str, workers: Sequence[str]
    ) -> List[Assignment]:
        """Dual-model case (worker.py:303-480): fair split of the pool
        by predicted rate, then grow each model to its share, preempting
        the other model's workers when the split demands it."""
        cost_a = self.costs.get(model_a, ModelCost(0, 0, 0.001))
        cost_b = self.costs.get(model_b, ModelCost(0, 0, 0.001))
        weights = [self.worker_weights.get(w, 1.0) for w in workers]
        want_a, want_b, a_heavy = fair_split_weighted_directed(
            weights, cost_a, cost_b
        )
        # honor the split's placement direction: the model whose count
        # refers to the HEAVIEST slots must grow heaviest-first, the
        # other lightest-first, or a count like "1 = the weight-2
        # group" lands on an arbitrary single chip and the realized
        # split is worse than the unweighted reference's. With a
        # uniform pool the order stays untouched (reference behavior,
        # including which worker takes which batch).
        if any(x != 1.0 for x in weights):
            desc = sorted(
                workers,
                key=lambda w: (-self.worker_weights.get(w, 1.0), w),
            )
            asc = list(reversed(desc))
            workers_a = desc if a_heavy else asc
            workers_b = asc if a_heavy else desc
        else:
            workers_a = workers_b = list(workers)
        # cap wants by actual queue depth + what's already running
        running_a = [w for w, b in self.in_progress.items() if b.model == model_a and w in workers]
        running_b = [w for w, b in self.in_progress.items() if b.model == model_b and w in workers]
        want_a = min(want_a, len(self._queue(model_a)) + len(running_a))
        want_b = min(want_b, len(self._queue(model_b)) + len(running_b))
        out: List[Assignment] = []
        out += self._grow_to(model_a, want_a, model_b, workers_a)
        out += self._grow_to(model_b, want_b, model_a, workers_b)
        return out

    def _grow_to(
        self, model: str, want: int, victim_model: str, workers: Sequence[str]
    ) -> List[Assignment]:
        """Assign queued batches of `model` until it occupies `want`
        workers: free workers first, then preempt `victim_model`'s
        workers beyond *their* fair share (preempted batch returns to
        the front of its queue — reference worker.py:389-408)."""
        q = self._queue(model)
        out: List[Assignment] = []
        have = sum(
            1 for w, b in self.in_progress.items() if b.model == model and w in workers
        )
        # free workers first. The draw goes through _take_batches so
        # the per-class weighted split applies in dual-model rounds
        # too (an unclassed/single-class queue reduces to the exact
        # popleft order) — one model's queue being all batch-class
        # must not starve the other class just because a second model
        # is active.
        free = self._free_workers(workers)
        take = min(len(free), max(0, want - have), len(q))
        for w, batch in zip(free, self._take_batches(model, take)):
            self.in_progress[w] = batch
            out.append(Assignment(worker=w, batch=batch))
            have += 1
        # then preempt the other model's surplus workers
        if have < want and q:
            victims = [
                w
                for w, b in self.in_progress.items()
                if b.model == victim_model and w in workers
            ]
            n_victims = len(victims)
            surplus = victims[: max(0, n_victims - (len(workers) - want))]
            take = min(len(surplus), max(0, want - have), len(q))
            for w, batch in zip(surplus, self._take_batches(model, take)):
                # (no stage handling here: schedule() un-stages every
                # prefetch batch before a dual-model round can run)
                displaced = self.in_progress[w]
                self._queue(displaced.model).appendleft(displaced)
                _M_PREEMPTIONS.inc()
                self.in_progress[w] = batch
                out.append(Assignment(worker=w, batch=batch, preempted=displaced))
                have += 1
        return out

    def _record_rates(self, workers: Sequence[str]) -> None:
        """Per-round predicted-rate sample (reference worker.py:485-495)."""
        t = self.now()
        for model in self.active_models():
            cost = self.costs.get(model)
            if cost is None:
                continue
            n = sum(
                self.worker_weights.get(w, 1.0)
                for w, b in self.in_progress.items()
                if b.model == model and w in workers
            )
            self.rate_samples.setdefault(
                model, deque(maxlen=self.max_samples)
            ).append((t, query_rate(cost, n)))

    # ------------------------------------------------------------------
    # completion + failure (reference worker.py:989-1026, 1279-1306)
    # ------------------------------------------------------------------

    def on_batch_done(
        self, worker: str, job_id: int, batch_id: int, exec_time: float, n_images: int
    ) -> Optional[JobState]:
        """A worker ACKed a batch. Frees the worker, updates metrics;
        returns the JobState iff the whole job just completed."""
        cur = self.in_progress.get(worker)
        if cur is not None and cur.key == (job_id, batch_id):
            del self.in_progress[worker]
            # promote the staged batch: the worker moved on to it the
            # moment its previous inference finished
            nxt = self.prefetch.pop(worker, None)
            if nxt is not None:
                self.in_progress[worker] = nxt
        elif self.prefetch.get(worker) is not None and self.prefetch[
            worker
        ].key == (job_id, batch_id):
            # out-of-order ACK (the staged batch drained first): clear
            # the stage; the primary is still in flight on this worker
            del self.prefetch[worker]
        st = self.jobs.get(job_id)
        if st is None or batch_id in st.completed_batches:
            return None  # unknown job, already-finished job, or dup ACK
        st.completed_batches.add(batch_id)
        # the duplicate copy may still be queued (requeued after a
        # false suspicion) — drop it so no worker re-runs it
        q = self._queue(st.model)
        for b in list(q):
            if b.key == (job_id, batch_id):
                q.remove(b)
                break
        model = st.model
        self.query_counts[model] = self.query_counts.get(model, 0) + n_images
        t = self.now()
        samples = self.latency_samples.setdefault(
            model, deque(maxlen=self.max_samples)
        )
        samples.append((t, exec_time, n_images))
        # registry mirror of the C1/C2 console: counters + histograms
        # METRICS_PULL can merge cluster-wide. Only the LIVE
        # coordinator counts (shadow_prune deliberately does not, or a
        # standby's shadow would double every query in the aggregate)
        _M_QUERIES.inc(n_images, model=model)
        _M_BATCH_EXEC.observe(exec_time, model=model)
        if n_images > 0:
            _M_QUERY_LAT.observe(exec_time / n_images, model=model)
        self._refresh_rate_gauges()
        self._refresh_gauges()
        st.pending_batches -= 1
        if st.pending_batches <= 0 and not st.done:
            st.done = True
            _M_JOBS_DONE.inc(model=model)
            self._retire_job(job_id)
            return st
        return None

    def _retire_job(self, job_id: int) -> None:
        st = self.jobs.pop(job_id, None)
        if st is not None:
            self.done_jobs[job_id] = st
        while len(self.done_jobs) > self.max_done_jobs:
            del self.done_jobs[next(iter(self.done_jobs))]

    def job_state(self, job_id: int) -> Optional[JobState]:
        """In-flight or recently-finished job state (status endpoint)."""
        return self.jobs.get(job_id) or self.done_jobs.get(job_id)

    def on_batch_failed(self, worker: str, job_id: int, batch_id: int) -> Optional[Batch]:
        """A live worker reported it could not run its batch (e.g. no
        replica served an input): requeue at the front and free the
        worker, exactly like a worker death but scoped to the matching
        batch key."""
        cur = self.in_progress.get(worker)
        if cur is None or cur.key != (job_id, batch_id):
            staged = self.prefetch.get(worker)
            if staged is None or staged.key != (job_id, batch_id):
                return None
            # the STAGED batch failed (e.g. its prepare found no live
            # replica): clear the stage; the primary keeps running
            del self.prefetch[worker]
            cur = staged
        else:
            del self.in_progress[worker]
            nxt = self.prefetch.pop(worker, None)
            if nxt is not None:
                # worker proceeds to its staged batch after the failure
                self.in_progress[worker] = nxt
        st = self.jobs.get(job_id)
        if st is None or batch_id in st.completed_batches:
            # unknown/retired job or already done elsewhere: free the
            # worker but never requeue (a deterministically-failing
            # orphan batch would loop forever)
            return None
        self._note_requeue(cur, worker)
        cur.failures += 1
        if cur.failures >= self.max_batch_failures:
            # deterministic failure: fail the JOB loudly; an infinite
            # fail/requeue loop would pin a worker forever while the
            # client waits
            self.fail_job(
                job_id,
                f"batch {batch_id} failed {cur.failures} times on live "
                "workers",
            )
            return None
        self._queue(cur.model).appendleft(cur)
        self.requeue_count += 1
        _M_REQUEUES.inc()
        self._refresh_gauges()
        return cur

    def fail_job(self, job_id: int, error: str) -> Optional[JobState]:
        """Retire a job as FAILED: record the error, purge its queued
        batches, notify path via pop_failed_jobs. Used by the
        coordinator (batch cap) and by the standby applying a
        JOB_FAILED_RELAY so failover can't resurrect the job."""
        st = self.jobs.get(job_id)
        if st is None:
            return None
        st.error = error
        st.done = True
        _M_JOBS_FAILED.inc(model=st.model)
        q = self._queue(st.model)
        for b in [b for b in q if b.job_id == job_id]:
            q.remove(b)
        self._retire_job(job_id)
        self._newly_failed.append(st)
        self._refresh_gauges()
        return st

    def pop_failed_jobs(self) -> List[JobState]:
        """Jobs failed since the last call (service notifies clients)."""
        out, self._newly_failed = self._newly_failed, []
        return out

    def on_worker_failed(self, worker: str) -> Optional[Batch]:
        """Worker died: requeue its in-flight batch at the FRONT
        (reference handle_failures_if_pending_status,
        worker.py:1279-1306). Returns the requeued batch, if any."""
        staged = self.prefetch.pop(worker, None)
        if staged is not None:
            self._queue(staged.model).appendleft(staged)
            self.requeue_count += 1
            _M_REQUEUES.inc()
            self._note_requeue(staged, worker)
        batch = self.in_progress.pop(worker, None)
        if batch is not None:
            # primary requeued after the staged batch so it lands at
            # the very front (it was assigned first)
            self._queue(batch.model).appendleft(batch)
            self.requeue_count += 1
            _M_REQUEUES.inc()
            self._note_requeue(batch, worker)
        self._refresh_gauges()
        return batch

    @staticmethod
    def _note_requeue(batch: Batch, worker: str) -> None:
        """Tail-exemplar marker per affected request trace: a requeue
        is exactly the event that explains a later deadline miss, so
        it is captured regardless of the head sampling decision."""
        for e in batch.traces:
            TRACER.note_exemplar(
                TraceContext.from_wire(e), "requeue",
                labels={"worker": worker, "job": batch.job_id,
                        "batch": batch.batch_id},
            )

    def drop_worker(self, worker: str) -> None:
        """Forget a worker without requeueing (voluntary leave after
        its batch was handled)."""
        self.in_progress.pop(worker, None)
        self.prefetch.pop(worker, None)

    # ------------------------------------------------------------------
    # standby shadow maintenance (reference worker.py:887-897, 965-986)
    # ------------------------------------------------------------------

    def shadow_prune(self, job_id: int, batch_id: int, n_images: int) -> None:
        """Standby side: the primary reported this batch complete —
        remove it wherever it is (queued here since the standby never
        assigns) and update the job count (reference worker.py:965-986)."""
        st = self.jobs.get(job_id)
        if st is None or batch_id in st.completed_batches:
            return
        st.completed_batches.add(batch_id)
        q = self._queue(st.model)
        for b in list(q):
            if b.key == (job_id, batch_id):
                q.remove(b)
                break
        self.query_counts[st.model] = self.query_counts.get(st.model, 0) + n_images
        st.pending_batches -= 1
        if st.pending_batches <= 0:
            st.done = True
            self._retire_job(job_id)

    # ------------------------------------------------------------------
    # metrics read-outs (C1/C2/C5; reference worker.py:1394-1428,
    # 1744-1808)
    # ------------------------------------------------------------------

    def c1_stats(self, window: float = 10.0) -> Dict[str, Dict[str, float]]:
        """Per-model query count + rate over the trailing window
        (reference C1, worker.py:1744-1787)."""
        t = self.now()
        out: Dict[str, Dict[str, float]] = {}
        for model in sorted(set(self.query_counts) | set(self.latency_samples)):
            recent = [
                n
                for (ts, _, n) in self.latency_samples.get(model, [])
                if ts >= t - window
            ]
            out[model] = {
                "total_queries": float(self.query_counts.get(model, 0)),
                "rate_per_sec": sum(recent) / window if window > 0 else 0.0,
            }
        return out

    def c2_stats(self, model: str) -> Dict[str, float]:
        """Mean/stdev/percentiles of per-image processing time
        (reference calculate_c2_command_params, worker.py:1394-1428)."""
        samples = self.latency_samples.get(model, [])
        per_image = [et / max(n, 1) for (_, et, n) in samples if n > 0]
        if not per_image:
            return {"count": 0.0}
        per_image.sort()

        def pct(p: float) -> float:
            i = min(len(per_image) - 1, max(0, int(round(p * (len(per_image) - 1)))))
            return per_image[i]

        return {
            "count": float(len(per_image)),
            "mean": statistics.fmean(per_image),
            "stdev": statistics.stdev(per_image) if len(per_image) > 1 else 0.0,
            "p25": pct(0.25),
            "p50": pct(0.50),
            "p75": pct(0.75),
            "p90": pct(0.90),
            "p99": pct(0.99),
        }

    def c5_assignments(self) -> Dict[str, Any]:
        """Current worker -> batch map (reference C5, worker.py:1807-1808)."""
        out = {
            w: {"job": b.job_id, "batch": b.batch_id, "model": b.model, "images": len(b.files)}
            for w, b in sorted(self.in_progress.items())
        }
        for w, b in sorted(self.prefetch.items()):
            out[f"{w} (staged)"] = {
                "job": b.job_id, "batch": b.batch_id, "model": b.model,
                "images": len(b.files), "staged": True,
            }
        return out

    def queue_depths(self) -> Dict[str, int]:
        return {m: len(q) for m, q in self.queues.items() if q}

    def batch_size_of(self, model: str) -> int:
        cost = self.costs.get(model)
        return cost.batch_size if cost else 32

    def all_queued_batches(self) -> List[Batch]:
        return [b for q in self.queues.values() for b in q]

    # ------------------------------------------------------------------
    # snapshot / restore (net-new vs the reference, whose scheduler
    # state survives only leader failover via the hot-standby relays —
    # SURVEY §5 "Checkpoint/resume: ... not via disk". This makes the
    # job pipeline survive a FULL cluster restart: the coordinator
    # snapshots to the replicated store and a fresh leader restores.)
    # ------------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of all scheduling state. In-flight batches
        are folded back into their queue fronts (their workers won't
        exist after a restart — same semantics as worker failure)."""
        def batch_dict(b: Batch) -> Dict[str, Any]:
            return {
                "job_id": b.job_id, "batch_id": b.batch_id,
                "model": b.model, "files": list(b.files),
                "replicas": {f: list(r) for f, r in b.replicas.items()},
                "versions": dict(b.versions),
                "failures": b.failures,
                "affinity": b.affinity,
                "streams": {f: list(v) for f, v in b.streams.items()},
                "slo_class": b.slo_class,
                "traces": [dict(e) for e in b.traces],
            }

        queues: Dict[str, List[Dict[str, Any]]] = {
            m: [batch_dict(b) for b in q] for m, q in self.queues.items() if q
        }
        # staged batches fold in first so the in-progress primaries end
        # up ahead of them at the queue front
        for worker, b in self.prefetch.items():
            queues.setdefault(b.model, []).insert(0, batch_dict(b))
        for worker, b in self.in_progress.items():
            queues.setdefault(b.model, []).insert(0, batch_dict(b))
        return {
            "job_counter": self._job_counter,
            "queues": queues,
            "jobs": {
                str(j.job_id): {
                    "job_id": j.job_id, "model": j.model,
                    "requester": j.requester,
                    "total_queries": j.total_queries,
                    "pending_batches": j.pending_batches,
                    "done": j.done,
                    "error": j.error,
                    "completed_batches": sorted(j.completed_batches),
                }
                for j in self.jobs.values()
            },
            "query_counts": dict(self.query_counts),
            "costs": {
                m: {
                    "load_time": c.load_time, "first_query": c.first_query,
                    "per_query": c.per_query,
                    "download_time": c.download_time,
                    "batch_size": c.batch_size, "resident": c.resident,
                }
                for m, c in self.costs.items()
            },
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        """Load a snapshot(). Replaces queues/jobs/counters; metrics
        samples start fresh (rates are meaningless across a restart)."""
        self._job_counter = max(self._job_counter, int(snap["job_counter"]))
        for m, c in snap.get("costs", {}).items():
            self.costs[m] = ModelCost(**c)
        self.queues = {
            m: deque(Batch(**b) for b in batches)
            for m, batches in snap.get("queues", {}).items()
        }
        self.in_progress = {}
        self.prefetch = {}
        self.jobs = {}
        for j in snap.get("jobs", {}).values():
            completed = set(j.pop("completed_batches", []))
            state = JobState(**j)
            state.completed_batches = completed
            self.jobs[state.job_id] = state
        self.query_counts = dict(snap.get("query_counts", {}))
