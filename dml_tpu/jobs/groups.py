"""Worker groups: tensor-parallel multi-chip serving wired into the
cluster pipeline.

The reference serves one whole model replica per VM (reference
models.py:26,51); pod-scale TPU serving shards a model over the ICI
domain of a *group* of chips and schedules the group as one worker
(Kumar et al., "Scale MLPerf-0.6 models on Google TPU-v3 Pods" — the
serving unit is the pod slice, not the host). This module teaches the
cluster scheduler that shape:

- **Topology** lives in the spec (`config.WorkerGroupSpec`): which
  nodes pool their chips into one dp×tp serving group. It is static
  configuration, like the node table itself — so every role
  (coordinator, promoted standby, worker) derives the identical group
  view from spec + SWIM liveness, and the view trivially survives
  leader failover with no relay protocol.
- **GroupDirectory** is that derivation: a formed group (every member
  alive and schedulable) collapses to ONE scheduler pool slot — the
  deterministic primary (first member by unique name) — carrying the
  group's aggregate capacity as a fair-share weight
  (`cost_model.fair_split_weighted`). Losing any member DEGRADES the
  group: the survivors return to the pool as ordinary single-chip
  workers, and the coordinator requeues the primary's in-flight
  batches (the ICI mesh those batches were running on no longer
  exists). A member coming back re-forms the group automatically.
- **Execution**: the group primary serves batches on a
  `parallel.inference.ShardedInference` compiled for the group mesh
  with ``param_gather=True`` — weights stay tp-sharded in HBM (the
  memory win) but are all-gathered at forward entry, so group outputs
  are BITWISE EQUAL to the single-chip path. Degradation mid-batch
  surfaces as `GroupDegraded`, riding the existing
  WORKER_TASK_FAIL -> requeue-at-front machinery; completion dedup in
  the scheduler keeps every acked batch counted exactly once no
  matter how the group reshuffles mid-job.
- **Observability**: ``jobs_group_*`` metrics (formed gauge, member
  liveness, degradation/reform counters, group-served batch counter),
  `JobService.group_stats()` in the CLI ``breakdown`` verb, and the
  ``cluster_sharded_serving`` bench section (``python -m
  dml_tpu.jobs.groups`` on a virtual CPU mesh) whose output-equality
  flag tools/claim_check.py validates.

Module stays jax-free at import time (the chaos/CLI stub paths build
directories and stub group backends without touching a device); the
sharded backend imports jax lazily.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Awaitable, Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..config import ClusterSpec, WorkerGroupSpec
from ..observability import METRICS

log = logging.getLogger(__name__)

_M_FORMED = METRICS.gauge(
    "jobs_group_formed",
    "1 while every member of the group is alive and schedulable")
_M_ALIVE = METRICS.gauge(
    "jobs_group_members_alive", "live members of the group")
_M_DEGRADATIONS = METRICS.counter(
    "jobs_group_degradations_total",
    "times a formed group lost a member and fell back to single chips")
_M_REFORMS = METRICS.counter(
    "jobs_group_reforms_total",
    "times a degraded group re-formed (every member back alive)")
_M_GROUP_BATCHES = METRICS.counter(
    "jobs_group_batches_total",
    "batches served by a group's sharded engine, per group")
_M_GROUP_REQUEUES = METRICS.counter(
    "jobs_group_requeues_total",
    "primary in-flight batches requeued because the group degraded")
_M_GROUP_RESHAPES = METRICS.counter(
    "jobs_group_reshapes_total",
    "collapsed group re-formed to a different mesh shape "
    "(member loss, graceful leave, or absorbed joiner), per group")
_M_GROUP_RESHAPE_CHIPS = METRICS.gauge(
    "jobs_group_reshape_chips",
    "chips in the mesh a group is currently collapsed to "
    "(0 while not collapsed)")


def note_group_requeue(group: str) -> None:
    """Tick the degradation-requeue counter (called by the service
    when it requeues a degraded group primary's in-flight batch)."""
    _M_GROUP_REQUEUES.inc(group=group)


class GroupDegraded(RuntimeError):
    """A group member died out from under a sharded batch: the ICI
    mesh the batch was executing on no longer exists. Routed through
    the ordinary WORKER_TASK_FAIL -> requeue path."""


def reform_ladder(
    mesh, n_members: int, n_active: int
) -> Optional[Dict[str, int]]:
    """The best dp×tp(×pp) mesh `n_active` of `n_members` members
    still support — the adaptive re-formation rung a degraded group
    steps down to instead of collapsing all the way to single chips
    (MLPerf TPU-pod practice: re-forming to a different slice shape
    is an operation, not a failure mode).

    Chips-per-member comes from the configured mesh's total extent
    spread over the configured membership (a -1 axis fills to the
    member count). The ladder prefers, in order: the most usable
    chips, the widest surviving ``tp`` (weight shards stay as thin as
    the original layout budgeted per-chip HBM for), then the deepest
    surviving ``pp`` — with tp'/pp' restricted to divisors of the
    configured axes so re-sharding stays a pure re-grouping of the
    same parameter tree (which is what keeps outputs token/bitwise
    identical through ``param_gather`` re-sharding). Returns None
    when fewer than two members survive (single-chip fallback) or the
    group was never degraded."""
    if n_members <= 0 or n_active < 2 or n_active >= n_members:
        return None
    total = 1
    free = False
    for v in (mesh.dp, mesh.tp, mesh.pp):
        if v == -1:
            free = True
        else:
            total *= max(1, v)
    if free:
        total = max(total, n_members)
    cpm = max(1, total // n_members)
    usable = cpm * n_active
    tp0 = max(1, mesh.tp)
    pp0 = max(1, mesh.pp)
    tp_divs = [d for d in range(tp0, 0, -1) if tp0 % d == 0]
    pp_divs = [d for d in range(pp0, 0, -1) if pp0 % d == 0]
    for use in range(usable, 1, -1):
        for tp_ in tp_divs:
            for pp_ in pp_divs:
                if use % (tp_ * pp_) == 0:
                    return {"dp": use // (tp_ * pp_), "tp": tp_,
                            "pp": pp_}
    return None


class GroupDirectory:
    """The runtime group view every role derives from spec + liveness.

    Pure bookkeeping — no sockets, no devices. `collapse` is the one
    entry the scheduler path uses per round; `on_node_failed` is the
    SWIM-callback fast path (degrade NOW, don't wait a round);
    `observe_ack` folds worker-advertised capacity from task ACKs so
    a coordinator promoted mid-job still learns measured capacities.
    """

    def __init__(self, spec: ClusterSpec):
        self.spec = spec
        #: operator/bench kill switch: disabled => every node serves
        #: as its own single-chip worker (the reference shape)
        self.enabled = True
        # group -> capacity advertised in task ACKs (None until heard)
        self._observed: Dict[str, Dict[str, Any]] = {}
        self._formed_last: Dict[str, bool] = {
            g.name: False for g in spec.worker_groups
        }
        self.degradations: Dict[str, int] = {}
        self.reforms: Dict[str, int] = {}
        #: reform ladder kill switch: off => member loss falls all
        #: the way back to single chips (the pre-elastic behavior)
        self.reform_enabled = True
        self.reshapes: Dict[str, int] = {}
        # group -> the mesh shape it is currently collapsed to:
        # "full" (configured mesh, all members), a reform-ladder dict
        # {dp,tp,pp}, or None (not collapsed — degraded/withheld)
        self._shape_last: Dict[str, Any] = {}
        # group -> members serving the current collapsed shape
        self._active_last: Dict[str, Tuple[str, ...]] = {}
        # collapse memo: the collapse is a pure function of (pool,
        # active LM models, enabled-flag, ACK-observed capacities) —
        # all captured by the caller-provided cache key (the service
        # keys on the SWIM view epoch + election roles). Without it a
        # large cluster pays the O(groups×members) re-derivation every
        # scheduling tick even when nothing changed.
        self._collapse_key: Optional[Tuple] = None
        self._collapse_cached: Optional[
            Tuple[List[str], Dict[str, float]]
        ] = None

    # -- static topology ----------------------------------------------

    def has_groups(self) -> bool:
        return self.enabled and bool(self.spec.worker_groups)

    def members(self, name: str) -> Tuple[str, ...]:
        return self.spec.group_members_unique(name)

    def primary(self, name: str) -> Optional[str]:
        mem = self.members(name)
        return mem[0] if mem else None

    def group_of(self, uname: str) -> Optional[WorkerGroupSpec]:
        if not self.enabled:
            return None
        return self.spec.group_of_unique(uname)

    def capacity(self, name: str) -> float:
        """Fair-share weight of the formed group: the capacity its
        primary advertised in task ACKs when heard, else the chip-count
        prior (one chip per member)."""
        obs = self._observed.get(name, {}).get("capacity")
        if obs:
            return float(obs)
        return float(max(len(self.members(name)), 1))

    def lm_serves(self, name: str, model: str) -> bool:
        """True when group `name` declares `model` in its
        ``lm_models`` — its engine serves that LM weight-resident
        tp-sharded, so LM rounds may keep it collapsed."""
        g = next(
            (g for g in self.spec.worker_groups if g.name == name), None
        )
        return g is not None and model in g.lm_models

    def roles_of(self, name: str) -> Dict[str, str]:
        """Disaggregation role per member (unique name ->
        "prefill"|"decode"); empty when the group is not role-split."""
        return self.spec.group_roles_unique(name)

    # -- scheduler-facing view ----------------------------------------

    def collapse(
        self,
        pool: Iterable[str],
        lm_active: Iterable[str] = (),
        cache_key: Optional[Tuple] = None,
    ) -> Tuple[List[str], Dict[str, float]]:
        """Collapse formed groups inside an eligible worker pool.

        Returns ``(pool', weights)``: members of a FORMED group (all
        members present in `pool`) are replaced by their primary alone,
        weighted by the group capacity; members of a degraded group
        stay as individual weight-1 workers. Order of survivors is
        preserved. Also drives the formed/degraded edge metrics.

        `lm_active` names the round's active LM serving models (the
        register_lm set). A group collapses for the round only if it
        declares EVERY one of them in ``WorkerGroupSpec.lm_models`` —
        its engine serves them weight-resident tp-sharded
        (inference/lm_sharded.py). A group that does not withholds
        its members as single-chip slots for the round (PR 5's
        behavior): collapsing would withdraw the lender and weight
        the primary at a capacity its engine never delivers for that
        model. Formed-state tracking (edges, gauges) is unaffected —
        LM-servability is a routing decision, not a liveness one.

        `cache_key` memoizes the derivation: when provided and equal
        to the previous call's key, the cached result returns without
        re-deriving (the service keys on the SWIM view epoch +
        election roles + the active-LM set, so large clusters stop
        paying O(groups×members) per scheduling tick). ACK-observed
        capacity changes invalidate the memo internally."""
        if cache_key is not None:
            full_key = (cache_key, self.enabled, tuple(sorted(lm_active)))
            if (
                self._collapse_key == full_key
                and self._collapse_cached is not None
            ):
                cached_pool, cached_w = self._collapse_cached
                return list(cached_pool), dict(cached_w)
        else:
            full_key = None
        pool = list(pool)
        if not self.has_groups():
            if full_key is not None:
                self._collapse_key = full_key
                self._collapse_cached = (list(pool), {})
            return pool, {}
        lm_set = set(lm_active)
        pool_set = set(pool)
        # formed-state of EVERY configured group, not just those with
        # a member in the pool: a group whose members are all alive
        # but ineligible (promoted to leader/standby) must show — and
        # count — a degradation edge, or breakdown/gauges report a
        # serving group that nothing can serve on
        formed_now: Dict[str, bool] = {}
        collapses: Dict[str, bool] = {}
        active_now: Dict[str, Tuple[str, ...]] = {}
        shape_now: Dict[str, Any] = {}
        for g in self.spec.worker_groups:
            mem = self.members(g.name)
            present = tuple(m for m in mem if m in pool_set)
            formed_now[g.name] = bool(mem) and len(present) == len(mem)
            # the shape is a pure function of spec + LIVENESS — never
            # of the round's LM set — so the bookkeeping (reshape
            # edges, active members, on_node_failed's requeue latch)
            # is identical no matter which caller derives it (the
            # lm-aware scheduling tick vs group_stats' lm-blind live
            # refresh); the LM gate applies only to the POOL output
            # below
            shape = None
            if formed_now[g.name]:
                shape = "full"
            elif (
                self.reform_enabled
                and mem
                and mem[0] in present  # the group engine lives on the
                # primary; losing it IS the single-chip fallback
            ):
                shape = reform_ladder(g.mesh, len(mem), len(present))
            if shape is not None:
                active_now[g.name] = present
            # pool gating: a FULL group collapses when it serves every
            # active LM model (PR-5/6 round-aware rule); a REFORMED
            # group serves image rounds only — resident-sharded LM
            # engines are fixed-mesh, so LM rounds keep the
            # single-chip slots
            if shape == "full":
                collapses[g.name] = (
                    not lm_set or lm_set <= set(g.lm_models)
                )
            else:
                collapses[g.name] = shape is not None and not lm_set
            shape_now[g.name] = shape
            _M_ALIVE.set(len(present), group=g.name)
        out: List[str] = []
        weights: Dict[str, float] = {}
        for w in pool:
            g = self.spec.group_of_unique(w)
            if g is None or not collapses[g.name]:
                out.append(w)  # ungrouped, degraded, or LM-withheld
            elif w == self.members(g.name)[0]:
                out.append(w)  # the group's one pool slot
                shape = shape_now[g.name]
                if shape == "full":
                    weights[w] = self.capacity(g.name)
                else:
                    # reformed: weight by the reform mesh's chip
                    # count — the survivors' actual strength, not the
                    # full group's ACK-advertised capacity
                    weights[w] = float(
                        shape["dp"] * shape["tp"] * shape["pp"]
                    )
            # collapsed lenders are pooled under the primary: no slot
        for name, formed in formed_now.items():
            self._note_edge(name, formed)
            self._note_shape(name, shape_now.get(name),
                             active_now.get(name, ()))
        if full_key is not None:
            # un-keyed calls (group_stats' live refresh) must not
            # clobber the scheduling tick's memo — they would force a
            # full re-derivation every tick whenever breakdown polls
            self._collapse_key = full_key
            self._collapse_cached = (list(out), dict(weights))
        return out, weights

    def role_in(self, pool: Iterable[str], uname: str) -> Optional[str]:
        """This node's serving role given an eligible pool: "primary"
        (serves on the group engine — at full strength or on a
        reform-ladder mesh), "lender" (chips pooled under the
        primary), "degraded" (group configured but neither formed nor
        reformable), or None (not in any group)."""
        g = self.group_of(uname)
        if g is None:
            return None
        mem = self.members(g.name)
        pool_set = set(pool)
        present = tuple(m for m in mem if m in pool_set)
        collapsed = bool(mem) and (
            len(present) == len(mem)
            or (
                self.reform_enabled
                and mem[0] in present
                and reform_ladder(g.mesh, len(mem), len(present))
                is not None
            )
        )
        if not collapsed or uname not in present:
            return "degraded"
        return "primary" if uname == mem[0] else "lender"

    def is_reformed(self, name: str) -> bool:
        """True while the group's last derived shape is a
        reform-ladder mesh rather than its full configured one.
        Observability surface (group_stats, tests): the memo behind
        it refreshes only on nodes that run the collapse, so ROUTING
        decisions must not read it — the service's per-batch LM gate
        (service._group_serves) derives full-strength liveness
        directly from spec + alive instead."""
        shape = self._shape_last.get(name)
        return shape is not None and shape != "full"

    def active_members(self, name: str) -> Tuple[str, ...]:
        """The members serving the group's current collapsed shape
        (empty while not collapsed)."""
        return self._active_last.get(name, ())

    # -- liveness edges -----------------------------------------------

    def _note_edge(self, name: str, formed: bool) -> None:
        last = self._formed_last.get(name)
        if formed and not last:
            if self.degradations.get(name):
                self.reforms[name] = self.reforms.get(name, 0) + 1
                _M_REFORMS.inc(group=name)
                log.info("group %s re-formed", name)
        elif last and not formed:
            self.degradations[name] = self.degradations.get(name, 0) + 1
            _M_DEGRADATIONS.inc(group=name)
            log.warning(
                "group %s lost full strength: the reform ladder "
                "re-shapes onto the survivors where it can, else "
                "serving falls back to single-chip engines", name,
            )
        self._formed_last[name] = formed
        _M_FORMED.set(1.0 if formed else 0.0, group=name)

    def _note_shape(self, name: str, shape: Any,
                    active: Tuple[str, ...]) -> None:
        """Track the mesh a group is collapsed to; a transition
        between two DIFFERENT collapsed shapes (full -> reformed,
        reformed -> smaller, reformed -> full) is a RESHAPE — the
        observable edge of adaptive re-formation."""
        last = self._shape_last.get(name)
        if shape is not None and last is not None and shape != last:
            self.reshapes[name] = self.reshapes.get(name, 0) + 1
            _M_GROUP_RESHAPES.inc(group=name)
            log.info(
                "group %s RESHAPED %s -> %s on members %s",
                name, last, shape, list(active),
            )
        self._shape_last[name] = shape
        self._active_last[name] = tuple(active)
        if shape == "full":
            g = next(
                (g for g in self.spec.worker_groups if g.name == name),
                None)
            chips = float(len(active)) if g is None else float(
                max(1, g.mesh.dp) * max(1, g.mesh.tp)
                * max(1, g.mesh.pp)
                if -1 not in (g.mesh.dp, g.mesh.tp, g.mesh.pp)
                else len(active))
        elif shape is not None:
            chips = float(shape["dp"] * shape["tp"] * shape["pp"])
        else:
            chips = 0.0
        _M_GROUP_RESHAPE_CHIPS.set(chips, group=name)

    def on_node_failed(self, uname: str) -> Optional[Tuple[str, str]]:
        """SWIM failure fast path: if the dead node belonged to a
        currently-collapsed group (full or reformed), note the edge
        NOW and return ``(group_name, primary)`` so the coordinator
        can requeue the primary's in-flight batches without waiting
        for the next scheduling round to notice — whatever mesh those
        batches were running on no longer exists either way."""
        g = self.group_of(uname)
        if g is None:
            return None
        active = self._active_last.get(g.name, ())
        was_formed = bool(self._formed_last.get(g.name))
        if was_formed:
            self._note_edge(g.name, False)
        if not was_formed and uname not in active:
            return None  # not serving a collapsed mesh: nothing to requeue
        if uname in active:
            # latch the death out of the active set so a repeated
            # callback for the same corpse doesn't requeue twice; the
            # next collapse derives the new shape (reform or fallback)
            self._active_last[g.name] = tuple(
                m for m in active if m != uname
            )
        return g.name, self.primary(g.name) or uname

    # -- ACK-advertised capacity --------------------------------------

    def observe_ack(self, sender: str, data: Dict[str, Any]) -> None:
        """Fold a worker task ACK's group advertisement (group name +
        capacity) into the directory. This is how a coordinator —
        including one promoted mid-job by a failover — learns measured
        group capacity without any dedicated protocol."""
        name = data.get("group")
        if not name:
            return
        try:
            cap = float(data.get("group_capacity") or 0.0)
        except (TypeError, ValueError):
            cap = 0.0
        prev = self._observed.get(name, {}).get("capacity")
        self._observed[name] = {
            "capacity": cap if cap > 0 else None,
            "size": data.get("group_size"),
            "sender": sender,
            "at": time.time(),
        }
        if self._observed[name]["capacity"] != prev:
            # capacity feeds the collapse weights: a changed advert
            # must invalidate the memoized collapse, whose cache key
            # (SWIM epoch + roles) cannot see it
            self._collapse_key = None

    # -- operator surface ---------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """CLI `breakdown` topology line: per group, the configured
        members + mesh, the primary, formed-state, capacity in force,
        and the degradation/reform history."""
        out: Dict[str, Any] = {}
        for g in self.spec.worker_groups:
            mem = self.members(g.name)
            out[g.name] = {
                "members": list(mem),
                "primary": mem[0] if mem else None,
                "mesh": {"dp": g.mesh.dp, "tp": g.mesh.tp,
                         "pp": g.mesh.pp},
                "lm_models": list(g.lm_models),
                "roles": self.spec.group_roles_unique(g.name),
                "formed": bool(self._formed_last.get(g.name)),
                "capacity": self.capacity(g.name),
                "capacity_source": (
                    "ack" if self._observed.get(g.name, {}).get("capacity")
                    else "chip-count prior"
                ),
                "degradations": self.degradations.get(g.name, 0),
                "reforms": self.reforms.get(g.name, 0),
                # adaptive re-formation surface: the mesh the group is
                # collapsed to right now ("full" | {dp,tp,pp} | None),
                # who serves it, and how often the shape has changed
                "mesh_in_force": self._shape_last.get(g.name),
                "active_members": list(self._active_last.get(g.name, ())),
                "reshapes": self.reshapes.get(g.name, 0),
            }
        if not self.enabled and self.spec.worker_groups:
            out["_disabled"] = True
        return out


# ----------------------------------------------------------------------
# group inference backends
# ----------------------------------------------------------------------

#: (files_dict, exec_time_s, cost_constants_or_None) — the JobService
#: InferBackend contract (service.py)
_Backend = Callable[..., Awaitable[Tuple[Dict[str, Any], float, Optional[Dict[str, float]]]]]


def _check_members(
    group_name: str, members: Tuple[str, ...],
    alive_fn: Callable[[], Set[str]],
) -> None:
    alive = alive_fn()  # one snapshot: atomic view, not N rebuilds
    dead = [m for m in members if m not in alive]
    if dead:
        raise GroupDegraded(
            f"group {group_name} lost member(s) {dead}: the sharded "
            "mesh is gone; batch requeues onto the degraded pool"
        )


def stub_group_backend(
    group_name: str,
    members,
    alive_fn: Callable[[], Set[str]],
    per_file_s: float = 0.004,
    capacity: Optional[float] = None,
):
    """Deterministic group-engine stand-in for chaos/sim runs: the
    single-chip stub's latency divided by the group capacity
    (aggregate throughput), with member liveness checked before AND
    after the simulated device time — a member dying mid-batch breaks
    the mesh exactly like real ICI loss, surfacing `GroupDegraded`.

    Reform-aware: the batch serves on the ACTIVE member set (members
    ∩ alive — the same spec+liveness derivation the coordinator's
    reform ladder uses), scaling throughput to the survivors; the set
    CHANGING across the batch raises `GroupDegraded` (the mesh the
    batch was running on is gone, whichever direction it changed).
    Fewer than two live members = no sharded mesh at all. `members`
    may be a callable so elastic membership (leave strips members,
    joins absorb) is re-read per batch, matching the spec-derived
    coordinator view."""
    members_fn = members if callable(members) else (lambda: members)

    def _active() -> Tuple[str, ...]:
        alive = alive_fn()
        return tuple(m for m in members_fn() if m in alive)

    async def backend(model: str, paths: List[str]):
        mem = members_fn()
        active = _active()
        if len(active) < min(2, len(mem)):
            dead = [m for m in mem if m not in active]
            raise GroupDegraded(
                f"group {group_name} lost member(s) {dead}: "
                f"{len(active)} left — no sharded mesh; batch "
                "requeues onto the pool"
            )
        cap = float(
            capacity if capacity is not None else max(len(active), 1)
        )
        backend.capacity = cap
        exec_time = per_file_s * max(1, len(paths)) / cap
        await asyncio.sleep(exec_time)
        if _active() != active:
            raise GroupDegraded(
                f"group {group_name} membership changed mid-batch "
                f"({list(active)} -> {list(_active())}): the mesh the "
                "batch ran on is gone; batch requeues"
            )
        results = {p: [{"label": model, "score": 1.0}] for p in paths}
        _M_GROUP_BATCHES.inc(group=group_name)
        return results, exec_time, None

    backend.capacity = float(
        capacity if capacity is not None
        else max(len(members_fn()), 1)
    )
    backend.group_name = group_name
    # the stub echoes whatever model it is asked for, so it serves any
    # (the real sharded_backend pins `model` to its compiled engine)
    backend.model = None
    return backend


def _sharded_run(si, paths: List[str], size: Tuple[int, int]):
    """Decode -> sharded forward -> engine-shaped top-5 rows: the one
    execution body both group backends share (thread context). The
    result-dict shape is the service's re-key contract — keep it in
    exactly one place."""
    from ..models.labels import decode_predictions
    from ..models.preprocess import load_images

    t0 = time.monotonic()
    imgs = load_images(list(paths), size)
    probs = si(imgs)
    infer_time = time.monotonic() - t0
    top5 = decode_predictions(probs)
    return {
        p: [
            {"wnid": w, "label": lbl, "score": s}
            for (w, lbl, s) in t
        ]
        for p, t in zip(paths, top5)
    }, infer_time


def sharded_backend(
    si,  # parallel.inference.ShardedInference
    *,
    group_name: Optional[str] = None,
    members: Tuple[str, ...] = (),
    alive_fn: Optional[Callable[[], Set[str]]] = None,
    input_size: Optional[Tuple[int, int]] = None,
):
    """JobService `InferBackend` over a `ShardedInference`: decode the
    batch's images, run the mesh-sharded forward, emit the engine-shaped
    top-5 result rows. With ``param_gather=True`` meshes the rows are
    bitwise-identical to the single-chip path (same decode, same
    program, same float serialization).

    `input_size` overrides the model's native decode size (tiny shapes
    for dryruns/tests). When `members`/`alive_fn` are given, member
    liveness is checked around the device call so a mid-batch group
    degradation raises `GroupDegraded` instead of acking a result the
    broken mesh could not actually have produced."""
    mesh_shape = dict(si.mesh.shape)
    cap = float(mesh_shape.get("dp", 1) * mesh_shape.get("tp", 1))
    size = tuple(input_size or si.spec.input_size)

    def _check() -> None:
        if members and alive_fn is not None:
            _check_members(group_name or "?", members, alive_fn)

    async def backend(model: str, paths: List[str]):
        _check()
        results, infer_time = await asyncio.to_thread(
            _sharded_run, si, paths, size
        )
        _check()
        if group_name:
            _M_GROUP_BATCHES.inc(group=group_name)
        return results, infer_time, None

    backend.capacity = cap
    backend.group_name = group_name
    # one ShardedInference serves exactly one model: the service must
    # route only this model's batches here (anything else would run
    # the wrong forward and ack wrong predictions under the job)
    backend.model = si.spec.name
    return backend


def group_engine_backend(
    group_name: str,
    members,
    alive_fn: Callable[[], Set[str]],
    mesh_spec,  # config.MeshSpec — the group's dp×tp layout
    batch_size: int = 32,
    seed: int = 0,
):
    """The production group engine for CLI/NodeApp primaries: a lazy
    MULTI-model sharded backend. On the first batch of each model it
    builds (and caches) a ``param_gather=True`` `ShardedInference`
    over the group mesh resolved from this host's visible devices, so
    any registry CNN serves sharded without per-model wiring
    (``backend.model = None`` — the service routes every non-LM model
    here). Weights init seed-deterministically (like
    `LMBackend.from_spec`), so a rebuilt/restarted primary serves the
    identical function until explicit weights arrive; published
    weights flow through the ordinary load-model path — the service
    calls ``backend.set_variables(model, tree)`` after a
    `load_model_weights`, which rebuilds that model's group engine on
    the fetched tree (group-served and single-chip answers must come
    from the same weights, or formation state would change what a
    query returns). `backend.capacity` starts at the chip-count prior
    and updates to the resolved mesh size after the first build —
    task ACKs read it per batch, so the fair-share weight
    self-corrects.

    Without this, a spec-configured group on a plain CLI node would
    COLLAPSE the pool (lenders withdrawn, primary weighted at group
    capacity) while the primary still served single-chip — less
    throughput than no groups at all.

    Reform-aware: each batch derives the ACTIVE member set (members ∩
    alive, same derivation as the coordinator's reform ladder) and
    compiles/caches one engine per (model, reformed mesh). The
    variables tree is identical across shapes (seed-deterministic, or
    the one operator-loaded tree), so ``param_gather`` keeps reformed
    outputs bitwise-equal to the full-mesh — and single-chip — path;
    re-sharding changes WHERE weight shards live, never the math."""
    from ..config import MeshSpec

    members_fn = members if callable(members) else (lambda: members)
    cache: Dict[Tuple[str, Tuple[int, int, int]], Any] = {}
    explicit: Dict[str, Any] = {}  # model -> operator-loaded tree

    def _mesh_for(n_active: int, n_members: int):
        """The mesh to serve on at this strength: the configured
        layout at full membership, the reform-ladder rung otherwise
        (None = no viable sharded mesh)."""
        if n_active >= n_members:
            return mesh_spec
        rung = reform_ladder(mesh_spec, n_members, n_active)
        if rung is None:
            return None
        return MeshSpec(dp=rung["dp"], tp=rung["tp"], pp=rung["pp"])

    def _build(model: str, use_mesh):
        import jax

        from ..parallel.inference import ShardedInference
        from ..parallel.mesh import make_mesh

        devices = jax.devices()
        sizes = (use_mesh.dp, use_mesh.tp, use_mesh.sp,
                 use_mesh.pp, use_mesh.ep)
        if -1 not in sizes:
            # a fully-specified group mesh takes its chip count off
            # the front of the host's device list (a -1 axis fills
            # with everything visible)
            want = 1
            for s in sizes:
                want *= s
            if len(devices) < want:
                raise RuntimeError(
                    f"group {group_name} mesh needs {want} "
                    f"devices, host sees {len(devices)}"
                )
            devices = devices[:want]
        mesh = make_mesh(use_mesh, devices=devices)
        si = ShardedInference(
            model, mesh, batch_size=batch_size, seed=seed,
            variables=explicit.get(model), param_gather=True,
        )
        cache[(model, (use_mesh.dp, use_mesh.tp, use_mesh.pp))] = si
        backend.capacity = float(
            mesh.shape.get("dp", 1) * mesh.shape.get("tp", 1)
        )
        return si

    async def backend(model: str, paths: List[str]):
        mem = members_fn()
        alive = alive_fn()
        active = tuple(m for m in mem if m in alive)
        use_mesh = _mesh_for(len(active), max(len(mem), 1))
        if use_mesh is None:
            raise GroupDegraded(
                f"group {group_name} has {len(active)} live "
                "member(s): no sharded mesh; batch requeues"
            )

        def run():
            key = (model, (use_mesh.dp, use_mesh.tp, use_mesh.pp))
            si = cache.get(key) or _build(model, use_mesh)
            return _sharded_run(si, paths, si.spec.input_size)

        results, infer_time = await asyncio.to_thread(run)
        now_active = tuple(m for m in members_fn() if m in alive_fn())
        if now_active != active:
            raise GroupDegraded(
                f"group {group_name} membership changed mid-batch: "
                "the mesh the batch ran on is gone; batch requeues"
            )
        _M_GROUP_BATCHES.inc(group=group_name)
        return results, infer_time, None

    def set_variables(model: str, variables: Any) -> None:
        """Adopt operator-loaded weights (load-model): drop the cached
        engines (every shape) so the next batch rebuilds on this tree."""
        explicit[model] = variables
        for key in [k for k in cache if k[0] == model]:
            cache.pop(key, None)

    backend.capacity = float(max(len(members_fn()), 1))
    backend.group_name = group_name
    backend.model = None  # lazy per-model engines: serves any CNN
    backend.set_variables = set_variables
    return backend


def wire_group_backend(node) -> Optional[Any]:
    """Give a production node its group engine IF it is the primary
    of a configured worker group (CLI/NodeApp path): lenders and
    ungrouped nodes get None and serve single-chip. Membership is
    re-read from the spec per batch — elastic joins/leaves re-shape
    the group under a running engine."""
    spec = node.spec
    uname = node.me.unique_name
    g = spec.group_of_unique(uname)
    if g is None:
        return None
    members = spec.group_members_unique(g.name)
    if not members or uname != members[0]:
        return None
    return group_engine_backend(
        g.name,
        lambda: spec.group_members_unique(g.name),
        lambda: {n.unique_name for n in node.membership.alive_nodes()},
        g.mesh,
    )


# ----------------------------------------------------------------------
# bench: sharded cluster serving on a virtual CPU mesh
# (`python -m dml_tpu.jobs.groups` — bench.py runs it as a subprocess
# with JAX_PLATFORMS=cpu and 8 virtual devices, same pattern as
# tools/ring_vs_ulysses)
# ----------------------------------------------------------------------


def bench_sharded_serving(
    n_queries: int = 64,
    n_files: int = 16,
    base_port: int = 28941,
    image_size: Tuple[int, int] = (64, 64),
    batch: int = 8,
    model: str = "ResNet50",
    tmp: str = "/tmp/dml_tpu_bench_sharded",
) -> Dict[str, Any]:
    """End-to-end sharded cluster serving vs the single-chip pipeline.

    Stands up the SAME `chaos.LocalCluster` chassis the soaks
    validate — 5 nodes, H4+H5 pooled into one dp=1×tp=2 group whose
    primary serves on a ``param_gather`` ShardedInference — serves an
    image job through the full store/scheduler/ACK pipeline, then
    disables grouping and serves the identical job on single chips.
    Records q/s both ways, the group topology in force, and the
    output-equality flag (merged job outputs must match KEY FOR KEY,
    BIT FOR BIT — the param_gather contract) that
    tools/claim_check.py holds the artifact to. float32 so the
    equality claim is about reduction order, not dtype noise."""
    import os
    import shutil

    import jax
    import jax.numpy as jnp
    import numpy as np

    devices = jax.devices()
    if len(devices) < 2:
        return {
            "skipped": True,
            "reason": f"needs >= 2 devices for tp=2, have {len(devices)}",
        }

    from ..cluster.chaos import LocalCluster
    from ..config import MeshSpec, Timing, WorkerGroupSpec
    from ..parallel.inference import ShardedInference
    from ..parallel.mesh import make_mesh
    from .service import JobService

    from ..models.params_io import init_variables
    from ..models.registry import get_model

    spec = get_model(model)
    variables = init_variables(
        spec, seed=0, dtype=jnp.float32, image_size=image_size
    )
    mesh_group = make_mesh(MeshSpec(dp=1, tp=2), devices=devices[:2])
    mesh_one = make_mesh(MeshSpec(), devices=devices[:1])
    si_group = ShardedInference(
        model, mesh_group, batch_size=batch, variables=variables,
        dtype=jnp.float32, param_gather=True,
    )
    si_one = ShardedInference(
        model, mesh_one, batch_size=batch, variables=variables,
        dtype=jnp.float32,
    )
    # pay both compiles BEFORE the timed serves: the q/s ratio must
    # compare serving, not who ate the XLA warmup
    warm = np.zeros((1, *image_size, 3), np.uint8)
    si_group(warm)
    si_one(warm)
    group = WorkerGroupSpec("tp0", ("H4", "H5"), MeshSpec(dp=1, tp=2))

    async def run() -> Dict[str, Any]:
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        cluster = LocalCluster(
            5, tmp, base_port,
            timing=Timing(ping_interval=0.2, ack_timeout=0.3,
                          cleanup_time=1.0, leader_rpc_timeout=10.0),
            worker_groups=[group],
            make_jobs=lambda node, store: _make_sharded_jobs(
                node, store, JobService, si_group, si_one, group,
                image_size, model, batch,
            ),
        )
        try:
            await cluster.start()
            await cluster.wait_for(
                cluster.converged, 20.0, "sharded bench convergence"
            )
            stack = [sn for _, sn in sorted(cluster.nodes.items())]
            client = stack[-1]
            from PIL import Image

            rng = np.random.RandomState(0)
            for i in range(n_files):
                p = os.path.join(tmp, f"img_{i}.jpeg")
                Image.fromarray(
                    rng.randint(0, 255, (96, 96, 3), np.uint8)
                ).save(p)
                await client.store.put(p, f"img_{i}.jpeg")

            async def timed_job() -> Tuple[float, Dict[str, Any]]:
                t0 = time.monotonic()
                job_id = await client.jobs.submit_job(model, n_queries)
                done = await client.jobs.wait_job(job_id, timeout=600.0)
                wall = time.monotonic() - t0
                assert done["total_queries"] == n_queries
                merged = await client.jobs.get_output(
                    job_id, os.path.join(tmp, f"out_{job_id}.json")
                )
                return wall, merged

            wall_g, merged_g = await timed_job()
            leader = next(sn for sn in stack if sn.node.is_leader)
            group_stats = leader.jobs.group_stats()
            for sn in stack:
                sn.jobs.groups.enabled = False
            wall_s, merged_s = await timed_job()
            equal = merged_g == merged_s and bool(merged_g)
            return {
                "nodes": 5,
                "queries": n_queries,
                "model": model,
                "image_size": list(image_size),
                "groups": {
                    name: g for name, g in group_stats.items()
                    if isinstance(g, dict)
                },
                "qps_sharded": round(n_queries / wall_g, 1),
                "qps_single_chip": round(n_queries / wall_s, 1),
                "sharded_vs_single": round(wall_s / wall_g, 2),
                "equal_outputs": equal,
                "outputs_compared": len(merged_g),
                "note": "virtual CPU mesh (the bench chip is one "
                        "device); the equality flag is the product "
                        "claim — param_gather tp keeps group outputs "
                        "bit-identical to single-chip — while the q/s "
                        "ratio on shared-core CPU devices is an "
                        "honest lower bound, not the ICI story",
            }
        finally:
            await cluster.stop()

    return asyncio.run(run())


def _make_sharded_jobs(
    node, store, JobService, si_group, si_one, group: WorkerGroupSpec,
    image_size, model: str, batch: int,
):
    """Per-node JobService for the sharded bench/dryrun cluster: every
    node can serve single-chip batches on the 1-device engine; the
    group primary additionally carries the group's sharded engine."""
    uname = node.me.unique_name
    alive = lambda: {  # noqa: E731
        n.unique_name for n in node.membership.alive_nodes()
    }
    members = node.spec.group_members_unique(group.name)
    single = sharded_backend(si_one, input_size=image_size)
    gb = None
    if members and uname == members[0]:
        gb = sharded_backend(
            si_group, group_name=group.name, members=members,
            alive_fn=alive, input_size=image_size,
        )
    js = JobService(node, store, infer_backend=single, group_backend=gb)
    js.scheduler.set_batch_size(model, batch)
    return js


def _main() -> None:  # pragma: no cover - bench subprocess entry
    import json

    print(json.dumps(bench_sharded_serving(), default=str))


if __name__ == "__main__":  # pragma: no cover
    _main()
