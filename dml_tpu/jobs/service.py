"""Job service: attaches the ML job pipeline to a Node.

Rebuilds the reference's L7 I/O wiring (worker.py:176-537, 887-1059,
1356-1459, 1573-1627) on top of the pure-logic Scheduler:

- coordinator role (while node.is_leader): job intake, fair-share
  scheduling, ACK bookkeeping, completion notification, C1/C2/C3/C5
  metrics, standby relays
- worker role (every node): execute WORKER_TASK_REQUESTs — fetch the
  batch's images over the store data plane, run the batched forward on
  the TPU engine, PUT the output JSON into the replicated store, ACK
  the coordinator with timing
- standby role (the computed election runner-up): mirror the
  primary's queues from SUBMIT_JOB_RELAY / WORKER_TASK_ACK_RELAY so a
  failover resumes scheduling with no lost work (reference
  worker.py:887-897, 965-986; promotion worker.py:577-588)

TPU-specific deltas from the reference (SURVEY §7 hard part #2):
- "preemption" on a worker cancels only the host-side task; both
  models stay resident in HBM so the switch costs nothing (the
  reference pays a model reload per switch, which its cost model
  charges for)
- the scheduler's cost constants are *measured* on the device (engine
  warmup) and piggybacked on task ACKs back to the coordinator; the
  reference hardcodes CPU measurements (worker.py:57-89)
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Awaitable, Callable, Deque, Dict, List, Optional, Tuple

from ..config import NodeId
from ..cluster.node import Node
from ..cluster.store_service import StoreService, data_addr
from ..cluster.util import BoundedDict, leader_retry, reap_task
from ..cluster.wire import Message, MsgType
from ..models.registry import MODEL_REGISTRY, get_model
from ..observability import METRICS
from ..tracing import CURRENT_CTXS, TRACER, TraceContext
from ..autoscale import AutoscaleController
from ..signal import SignalPlane
from .train import TrainCoordinator
from .cost_model import ModelCost, overlap_headroom
from .groups import GroupDirectory, note_group_requeue
from .scheduler import Assignment, Batch, DepthController, Scheduler

log = logging.getLogger(__name__)

# Worker-side stage timings + counters (the registry form of the
# ACK-carried breakdown the coordinator folds into breakdown_stats);
# labeled by model so METRICS_PULL shows where each model's batch wall
# goes on every node
_M_BATCHES = METRICS.counter(
    "worker_batches_total", "batches executed on this node, per model")
_M_BATCH_FAILS = METRICS.counter(
    "worker_batch_failures_total",
    "batches this node reported as failed, per model")
_M_FETCH = METRICS.histogram(
    "worker_fetch_seconds", "store replica fetch per batch")
_M_INFER = METRICS.histogram(
    "worker_infer_seconds",
    "backend infer call per batch (device forward + dispatch)")
_M_PUT = METRICS.histogram(
    "worker_put_seconds", "output JSON write + replicated store PUT")
_M_ACKS = METRICS.counter(
    "coordinator_batch_acks_total",
    "worker batch ACKs processed by the coordinator, per model")
_M_CACHE_HITS = METRICS.counter(
    "worker_decode_cache_hits_total", "decoded-input cache hits")
_M_CACHE_MISSES = METRICS.counter(
    "worker_decode_cache_misses_total", "decoded-input cache misses")
_M_STREAM_TOKENS = METRICS.counter(
    "request_stream_tokens_total",
    "LM tokens pushed into per-request ingress token streams")

# (files_dict, exec_time_s, cost_constants_or_None)
InferBackend = Callable[[str, List[str]], Awaitable[Tuple[Dict[str, Any], float, Optional[Dict[str, float]]]]]


def _accepts_on_token(fn) -> bool:
    """Whether a serving callable declares the ``on_token`` streaming
    parameter (ingress/streaming.py contract). Checked against the
    callable that will actually run the batch — group engines and
    single-chip backends opt in independently. Reflection is paid once
    per callable: _execute memoizes through _group_token_aware."""
    try:
        import inspect

        return "on_token" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


class _StreamFanout:
    """Per-batch token-stream plumbing for ingress LM requests
    (dml_tpu/ingress/streaming.py): one data-plane StreamFeed per
    streaming request file, announced to the owning client
    (REQUEST_STREAM_READY) BEFORE decode begins, fed from the
    backend's ``on_token(local_path, text)`` callback — which may fire
    on the backend's decode thread, so every feed touch hops back to
    the loop. close() EOFs every feed (success or failure: the stream
    must always terminate)."""

    #: how long a closed stream's token stays pullable: covers a
    #: client whose READY push raced the decode but must not let a
    #: dead client pin the feed (and its buffered chunks) forever
    STREAM_TTL_S = 60.0

    def __init__(self, service: "JobService", batch, paths: List[str]):
        self._loop = asyncio.get_running_loop()
        self._service = service
        #: file -> [feed, ...]: one feed PER REQUEST, not per input —
        #: two streaming requests sharing a store input each get their
        #: own feed and READY push, fed the same tokens
        self.feeds: Dict[str, List[Any]] = {}
        self.tokens: List[str] = []
        self._path_to_file: Dict[str, str] = {}
        self._closed = False
        for p, f in zip(paths, batch.files):
            self._path_to_file.setdefault(p, f)
            self._path_to_file.setdefault(os.path.basename(p), f)
        dp = service.store.data_plane
        for f, targets in batch.streams.items():
            for target in targets:
                client, req_id = target[0], target[1]
                token, feed = dp.expose_stream()
                self.feeds.setdefault(f, []).append(feed)
                self.tokens.append(token)
                service.node.send_unique(
                    client, MsgType.REQUEST_STREAM_READY,
                    {"id": req_id, "host": service.node.me.host,
                     "port": dp.port, "token": token},
                )

    def on_token(self, path: str, text: str) -> None:
        feeds = self.feeds.get(self._path_to_file.get(path, path))
        if feeds:
            _M_STREAM_TOKENS.inc()
            data = text.encode("utf-8")
            for feed in feeds:
                self._loop.call_soon_threadsafe(feed.push, data)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for feeds in self.feeds.values():
            for feed in feeds:
                self._loop.call_soon_threadsafe(feed.close)
        # retire the tokens after a grace window: a connected puller
        # already drains to EOF; one whose READY push was lost (single
        # unacked datagram) or that died after submit would otherwise
        # leak the feed + buffered chunks in DataPlane._streams forever
        tokens = list(self.tokens)
        service = self._service

        async def reap() -> None:
            await asyncio.sleep(_StreamFanout.STREAM_TTL_S)
            for t in tokens:
                service.store.data_plane.unexpose_stream(t)

        self._loop.call_soon_threadsafe(
            lambda: service._spawn_bg(reap(), "stream-token ttl")
        )


class JobService:
    """One per node. Acts in coordinator/worker/standby roles depending
    on the node's current cluster position."""

    def __init__(
        self,
        node: Node,
        store: StoreService,
        infer_backend: Optional[InferBackend] = None,
        image_patterns: Tuple[str, ...] = ("*.jpeg", "*.jpg"),
        engine=None,
        pipeline_depth: Optional[int] = None,
        group_backend: Optional[InferBackend] = None,
    ):
        """`engine` shares one InferenceEngine across co-located
        services (one weights copy + one compile per model per chip).

        `pipeline_depth=None` (default) runs the ADAPTIVE controller:
        the coordinator probes depth-1 vs depth-2 on real batches at
        job warmup, commits to the measured winner, and re-probes when
        the ACK-carried stage walls drift (DepthController — the
        worker-pipeline analog of `engine.choose_dispatch_mode`).
        An explicit int pins a STATIC depth: 1 restores the
        reference's strict one-outstanding-batch worker loop
        (worker.py:518-537), >1 forces staging batch N+1's store-fetch
        + host JPEG decode + device dispatch under batch N's in-flight
        inference. Through a high-latency device link the blocking
        per-batch round-trip is the cluster-serving bottleneck and
        overlap wins; on a fast link the overlap state machine can
        LOSE (r5 measured 0.91×/0.85×) — which is why measured, not
        assumed, is the default.

        `group_backend` is this node's tensor-parallel GROUP engine
        (jobs/groups.py `sharded_backend` over the group mesh): used
        for a batch only while this node is the PRIMARY of a formed
        worker group; every other situation (lender, degraded group,
        no group) serves on the ordinary single-chip backend. The
        directory view driving that choice is derived from spec +
        SWIM liveness, so it needs no relay protocol to survive
        failover."""
        self.node = node
        self.store = store
        self.image_patterns = image_patterns
        self._backend = infer_backend or self._engine_backend
        self._backend_is_engine = infer_backend is None
        # worker-group subsystem: the directory every role derives
        # from spec + liveness, this node's group engine (primaries
        # only), and the per-round pool weights handed the scheduler
        self.groups = GroupDirectory(node.spec)
        self._group_backend = group_backend
        self._pool_weights: Dict[str, float] = {}
        # LM (or other non-CNN) serving models registered on this node:
        # per-model worker backend + per-model input-file patterns
        # (image jobs sample *.jpeg; LM jobs sample prompt-token files)
        self._extra_backends: Dict[str, InferBackend] = {}
        # per-model LM GROUP backends (weight-resident tp-sharded or
        # disaggregated decode — inference/lm_sharded.py): used for a
        # batch only while this node is the primary of a formed group
        # that declares the model in WorkerGroupSpec.lm_models
        self._lm_group_backends: Dict[str, InferBackend] = {}
        # per-model prefill-role backends (LMPrefillBackend): serve
        # LM_PREFILL_REQUEST from a disaggregated group's decode
        # primary by building + exposing the KV slab
        self._lm_prefill: Dict[str, Any] = {}
        # models whose backend declares `on_dispatch` (see register_lm)
        self._backend_dispatch_aware: Dict[str, bool] = {}
        # models whose backend declares `on_token` (per-token streaming
        # for ingress requests; see register_lm + _execute)
        self._backend_token_aware: Dict[str, bool] = {}
        # group-backend callable -> on_token capability: signature
        # reflection must not run per executed batch on the serving
        # path (the single-chip case caches at register_lm time)
        self._gb_token_aware: Dict[Any, bool] = {}
        self.model_patterns: Dict[str, Tuple[str, ...]] = {}
        self._engine = engine  # lazy InferenceEngine (imports jax on first use)
        # Decoded-input cache for the worker prepare stage, keyed by
        # (local path, mtime_ns, size, target hw). Store objects are
        # immutable per version (a re-PUT mints a new version and a
        # new local path), so hits are always coherent. The reference
        # workload wrap-around-samples a small file set per job
        # (worker.py:188-245) and its workers re-download + re-decode
        # every occurrence; serving hot immutable objects from a
        # decoded cache is the TPU-host analog of not doing that.
        # Budget is bytes of decoded uint8; 0 disables.
        self.decode_cache_bytes: int = 256 << 20
        self._decode_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        self._decode_cache_lock = threading.Lock()
        self._decode_cache_used = 0
        self.decode_cache_hits = 0
        self.decode_cache_misses = 0
        self.scheduler = Scheduler(costs=self._seed_costs())
        self.depth_ctl: Optional[DepthController] = None
        self.set_pipeline_depth(pipeline_depth)
        # worker-side execution state: running batches (primary + an
        # early-promoted staged batch draining concurrently, <= depth)
        # and the one staged batch whose prepare runs eagerly
        self._running: Dict[Tuple[int, int], asyncio.Task] = {}
        self._staged: Optional[
            Tuple[Tuple[int, int], Batch, str, asyncio.Task]
        ] = None
        self._bg_tasks: set = set()
        # client-side completion futures; bounded so fire-and-forget
        # submitters don't leak (evicted callers fall back to polling)
        self._job_done: BoundedDict = BoundedDict(1000)
        self._sched_task: Optional[asyncio.Task] = None
        # loss tolerance over the at-most-once UDP transport: the
        # coordinator re-sends un-ACKed assignments (covers both a lost
        # WORKER_TASK_REQUEST and a lost ACK; batch-completion dedup in
        # the scheduler absorbs the resulting re-execution), and every
        # assignment carries a monotonic seq so a reordered stale
        # request can't cancel a newer batch on the worker
        self._task_seq = itertools.count(1)
        # incarnation stamp: a restarted coordinator's seq counter
        # restarts at 1, so workers compare seqs only within one
        # incarnation (keyed per sender as (inc, last_seq))
        self._incarnation = int(time.time() * 1000)
        self._assigned_at: Dict[str, Tuple[Tuple[int, int], float]] = {}
        self._staged_at: Dict[str, Tuple[Tuple[int, int], float]] = {}
        # coordinator-side per-batch wall-time breakdown from ACKs
        # (fetch / backend / infer) — where cluster-serving time goes
        self.batch_timing: Deque[Dict[str, float]] = deque(maxlen=512)
        self._last_seq: Dict[str, Tuple[int, int]] = {}  # sender -> (inc, seq)
        self.task_resend_after = max(
            1.0, 4 * node.spec.timing.ping_interval
        )
        # submit idempotency tokens -> job id
        self._submit_tokens: BoundedDict = BoundedDict(1000)
        # job-terminal observers (request front door, dml_tpu/ingress/):
        # called as cb(job_state, last_worker_or_None) on the
        # coordinator whenever a job reaches a terminal state —
        # completion (last_worker = the ACKing node, the session-
        # affinity signal) or failure (None). Callbacks must not raise
        # (guarded anyway) and must not block (spawn their own tasks).
        self.on_job_done_cbs: List[Callable[[Any, Optional[str]], None]] = []
        # model -> pinned store version currently served (for recovery
        # after an eviction; "latest" is resolved at load time)
        self._served_weight_version: Dict[str, Optional[int]] = {}
        # --- shadow-restore relay protocol state ---
        # coordinator: every relay carries a generation; restore-jobs
        # bumps it, so "sent after the restore" is observable on the
        # standby regardless of datagram arrival order. Seeded from the
        # incarnation timestamp so a RESTARTED coordinator (same
        # host:port identity) starts above every generation it ever
        # sent before — otherwise the standby's _gen_stale would
        # silently drop all of the new incarnation's relays.
        self._relay_gen = self._incarnation
        # standby: recent relays (sender, gen, apply-fn, msg), kept so
        # a snapshot restore can replay everything sent at/after its
        # generation — relays race the snapshot fetch arbitrarily and
        # apply-fns are idempotent, so apply-now + replay-later is safe
        self._relay_log: Deque[Tuple[str, int, Any, Message]] = deque(maxlen=500)
        # while a restore is pending the bounded log is not enough:
        # >500 relays arriving before the snapshot replay runs would
        # evict entries the replay depends on. This side buffer holds
        # every relay from the FIRST fetch attempt of a generation
        # until that generation's replay succeeds (NOT per-fetch: the
        # coordinator retries failed fetches, and relays landing
        # between attempts need the same protection). Unbounded, but
        # its lifetime is one restore (seconds); replaying a relay
        # twice is safe because apply-fns are idempotent.
        self._restore_buffer: list = []
        self._restore_buffer_gen: Optional[int] = None
        self._shadow_restoring = False
        self._shadow_gen: Optional[int] = None  # last restored generation
        self._shadow_gen_leader: Optional[str] = None
        self._restored_keys: BoundedDict = BoundedDict(50)  # (leader, ver, gen)
        # SLO signal plane: windows sample on every node, burn/health
        # evaluation runs only while this node leads (signal.py)
        self.signal = SignalPlane(node, jobs=self)
        # closed-loop autoscaler (autoscale.py): adopts relayed
        # decisions everywhere, evaluates/actuates only while leading.
        # The capacity actuators stay None until the environment (the
        # chaos harness, the bench) wires real scale_out/scale_in
        # verbs — a bare cluster still gets reallocation + a typed
        # decision stream.
        self.autoscale = AutoscaleController(node, jobs=self, plane=self.signal)
        # elastic data-parallel training (train.py): registers the
        # trainer backend + SLO class on every node, drives runs and
        # adopts checkpointed ones only while this node leads
        self.train = TrainCoordinator(node, jobs=self)
        # chaos seam (`liar` event): stall each batch for this many
        # seconds AFTER measuring exec_time, so the self-reported wall
        # stays clean while the leader's dispatch->ACK observation
        # absorbs the stall — the forged-evidence straggler the
        # signal plane's cross-check must catch
        self.liar_extra_s: float = 0.0
        self._register()
        node.on_node_failed_cbs.append(self._on_node_failed)
        node.on_became_leader_cbs.append(self._on_became_leader)

    @staticmethod
    def _seed_costs() -> Dict[str, ModelCost]:
        """Registry priors; replaced by device measurements as ACKs
        arrive."""
        costs: Dict[str, ModelCost] = {}
        for spec in set(MODEL_REGISTRY.values()):
            c = spec.cost
            costs[spec.name] = ModelCost(
                load_time=c.load_time,
                first_query=c.first_query,
                per_query=c.per_query,
                download_time=c.download_time,
                batch_size=c.default_batch_size,
            )
        return costs

    async def start(self) -> None:
        self._sched_task = asyncio.create_task(
            self._schedule_loop(), name=f"{self.node.me}-sched"
        )
        self.signal.start()
        self.autoscale.start()
        self.train.start()
        interval = getattr(self.node.spec, "jobs_checkpoint_interval", 0.0)
        if interval and interval > 0:
            self._ckpt_task = asyncio.create_task(
                self._auto_checkpoint_loop(interval),
                name=f"{self.node.me}-autockpt",
            )

    async def _auto_checkpoint_loop(self, interval: float) -> None:
        """Periodic coordinator snapshots while work is in flight —
        the automated version of the checkpoint-jobs verb, so a full
        cluster restart can always restore the latest queues."""
        was_busy = False
        edge_pending = False
        while True:
            await asyncio.sleep(interval)
            if self._me != self.node.leader_unique:
                continue
            busy = bool(self.scheduler.jobs or self.scheduler.queue_depths())
            # busy-state observation is independent of snapshot success:
            # a failed tick must not suppress the busy->idle edge
            # snapshot (the drained state has to land eventually, or a
            # post-restart restore resurrects completed jobs)
            if busy:
                was_busy = True
            elif was_busy:
                was_busy = False
                edge_pending = True
            if not busy and not edge_pending:
                continue  # steady idle: latest snapshot already drained
            try:
                await self.checkpoint_jobs()
                if not busy:
                    edge_pending = False
            except Exception:
                log.exception("%s: auto checkpoint failed", self._me)

    async def stop(self) -> None:
        await self.train.stop()
        await self.autoscale.stop()
        await self.signal.stop()
        ct = getattr(self, "_ckpt_task", None)
        if ct is not None:
            await reap_task(ct, self._me, "checkpoint loop")
            self._ckpt_task = None
        if self._staged is not None:
            self._staged[3].cancel()
            self._staged = None
        for t in list(self._bg_tasks):
            t.cancel()
        for t in [self._sched_task] + list(self._running.values()):
            if t is not None:
                await reap_task(t, self._me, f"task {t.get_name()}")
        self._sched_task = None
        self._running.clear()

    # ------------------------------------------------------------------
    # roles
    # ------------------------------------------------------------------

    @property
    def _me(self) -> str:
        return self.node.me.unique_name

    def _eligible_workers(self) -> List[str]:
        """Live schedulable nodes = alive minus coordinator and
        standby (reference hardcodes H3..H10, worker.py:52). A cluster
        too small to spare dedicated coordinators uses every live
        node — this is also the single-node "leader = self" mode
        (SURVEY §7 minimum slice)."""
        alive = [n.unique_name for n in self.node.membership.alive_nodes()]
        leader = self.node.leader_unique
        sb = self.store.standby_node()
        standby = sb.unique_name if sb else None
        pool = [u for u in alive if u != leader and u != standby]
        return pool if pool else alive

    def worker_pool(self) -> List[str]:
        """The scheduler-visible pool: eligible nodes with every
        FORMED worker group collapsed to its primary (one slot, group
        capacity as its fair-share weight — jobs/groups.py). Members
        of a degraded group stay as ordinary single-chip slots. The
        weights of the returned pool are in `self._pool_weights`.

        Collapse is ROUND-aware per group: a round's active LM models
        (register_lm names) keep a group collapsed only if the group
        declares them ALL in ``WorkerGroupSpec.lm_models`` — its
        engine serves them weight-resident tp-sharded
        (inference/lm_sharded.py); any other group withholds its
        members as single-chip slots for the round (the PR-5
        fallback), because collapsing would withdraw the lender and
        weight the primary at a capacity its engine never delivers
        for that model. The token/bitwise-equality contracts make the
        per-batch engine choice (`_group_serves`) safe either way;
        THIS guard is about capacity accounting.

        The derivation memoizes on (SWIM view epoch, leader, standby,
        active-LM set): unchanged membership and roles return the
        cached pool instead of re-deriving O(groups×members) every
        scheduling tick."""
        eligible = self._eligible_workers()
        active = self.scheduler.active_models()
        lm_active = frozenset(
            m for m in active if m in self.model_patterns
        )
        sb = self.store.standby_node()
        cache_key = (
            self.node.membership.view_epoch,
            # elastic membership: a join/leave re-shapes groups and
            # pool slots without necessarily moving the SWIM view
            # epoch on this node first
            self.node.spec.universe_epoch,
            self.node.leader_unique,
            sb.unique_name if sb else None,
        )
        pool, self._pool_weights = self.groups.collapse(
            eligible, lm_active=lm_active, cache_key=cache_key
        )
        return pool

    def group_role(self) -> Optional[str]:
        """This node's serving role right now: "primary" (serves on
        the group engine), "lender", "degraded", or None."""
        return self.groups.role_in(self._eligible_workers(), self._me)

    def _group_backend_for(self, model: str) -> Optional[InferBackend]:
        """The group engine that would serve a batch of `model` on
        this node, if any: LM models route to their per-model sharded
        group backend (register_lm's `group_backend`, gated on the
        group declaring the model in lm_models); everything else to
        the CNN group engine."""
        if model in self._extra_backends:
            gb = self._lm_group_backends.get(model)
            if gb is None:
                return None
            g = self.groups.group_of(self._me)
            if g is None or model not in g.lm_models:
                return None
            return gb
        return self._group_backend

    def _group_token_aware(self, gb) -> bool:
        """Memoized _accepts_on_token for group backends: _execute
        asks per batch, signature reflection runs once per callable
        (an unhashable callable just pays it each time)."""
        try:
            return self._gb_token_aware[gb]
        except KeyError:
            pass
        except TypeError:
            return _accepts_on_token(gb)
        aware = _accepts_on_token(gb)
        self._gb_token_aware[gb] = aware
        return aware

    def _group_serves(self, model: str) -> bool:
        """True when a batch of `model` executing NOW would run on
        this node's group engine: a group backend is wired for it, it
        serves this model (gb.model pins a single compiled engine;
        None = any CNN), and this node is the primary of a formed
        group."""
        gb = self._group_backend_for(model)
        if gb is None:
            return False
        if getattr(gb, "model", None) not in (None, model):
            return False
        if model in self._extra_backends:
            # LM group engines are FIXED-mesh (weights resident,
            # sharded at registration): a group below full strength
            # (reform-ladder territory) must route LM batches to the
            # single-chip backend instead. Derived LIVE from spec +
            # liveness like role_in — the directory's collapsed-shape
            # memo only refreshes on nodes that run the collapse.
            g = self.groups.group_of(self._me)
            if g is not None:
                pool_set = set(self._eligible_workers())
                if not all(m in pool_set
                           for m in self.groups.members(g.name)):
                    return False
        return self.group_role() == "primary"

    def group_stats(self) -> Dict[str, Any]:
        """CLI `breakdown` topology line: configured groups, formed
        state, capacity in force, degradation/reform history. The
        directory's formed-state is normally refreshed by the
        scheduling loop — which runs the collapse only on the
        coordinator — so refresh it here first: `breakdown` must show
        LIVE topology on any node, not whatever this node last saw
        while it happened to be leader."""
        self.groups.collapse(self._eligible_workers())
        return self.groups.stats()

    # ------------------------------------------------------------------
    # client verbs (reference CLI submit-job / get-output /
    # predict-locally, worker.py:1744-1997)
    # ------------------------------------------------------------------

    def _canon(self, model: str) -> str:
        """Canonical model name: registry aliases resolve (resnet ->
        ResNet50); names registered via `register_lm` resolve
        case-insensitively (matching the registry's convention), and
        an unknown name's error lists them."""
        try:
            return get_model(model).name
        except KeyError:
            lm_names = set(self._extra_backends) | set(self.model_patterns)
            hit = {n.lower(): n for n in sorted(lm_names)}.get(model.lower())
            if hit is not None:
                return hit
            raise KeyError(
                f"unknown model {model!r}; registered LM models: "
                f"{sorted(lm_names) or 'none'}; CNN registry: "
                f"{sorted({s.name for s in MODEL_REGISTRY.values()})}"
            ) from None

    def register_lm(
        self,
        name: str,
        backend: Optional[InferBackend] = None,
        cost: Optional[Any] = None,
        patterns: Tuple[str, ...] = ("*.tokens.txt", "*.prompt.txt"),
        group_backend: Optional[InferBackend] = None,
        prefill: Optional[Any] = None,
    ) -> None:
        """Register an LM serving model as a first-class job type.

        Call on EVERY node with the same arguments (like the engine's
        CNN registry, which is implicitly shared): `backend` makes
        this node able to EXECUTE the model's batches (worker role),
        `cost` seeds the fair-share scheduler's plan wherever this
        node coordinates (leader or promoted standby; refined from
        ACK measurements either way), `patterns` tells the intake
        which store files are this model's inputs. After this,
        `submit-job <name> <N>` flows through the identical pipeline
        as image jobs — same batching, fair-share split, preemption,
        requeue-on-failure, standby relays, and get-output merge.

        `group_backend` (group PRIMARIES only) is this node's sharded
        LM group engine for the model — weight-resident tp-sharded
        decode or the disaggregated decode form
        (inference/lm_sharded.py). It serves a batch only while this
        node is the primary of a FORMED group declaring the model in
        ``WorkerGroupSpec.lm_models``; otherwise batches fall through
        to `backend` (single-chip), so degradation changes throughput,
        never answers. `prefill` (prefill-role members) is an
        `LMPrefillBackend` serving LM_PREFILL_REQUEST: it builds each
        batch's KV-cache slab and this service exposes the bytes on
        the data plane for the decode primary to pull."""
        if group_backend is not None:
            self._lm_group_backends[name] = group_backend
        if prefill is not None:
            self._lm_prefill[name] = prefill
        if backend is not None:
            self._extra_backends[name] = backend
            # Backends that declare an `on_dispatch` parameter (the
            # LMBackend contract) opt in to promote-at-dispatch: the
            # staged next batch starts the moment this batch's prompts
            # are submitted to the backend's continuous-batching
            # driver, instead of after its decode drains — the
            # generic-path analog of the engine path's
            # promote-at-dispatch (VERDICT r4 item 2).
            try:
                import inspect

                params = inspect.signature(backend).parameters
                self._backend_dispatch_aware[name] = "on_dispatch" in params
                # `on_token` (ingress/streaming.py contract): the
                # backend calls on_token(local_path, text) per decoded
                # token; the worker feeds each streaming request's
                # data-plane stream from it
                self._backend_token_aware[name] = "on_token" in params
            except (TypeError, ValueError):
                self._backend_dispatch_aware[name] = False
                self._backend_token_aware[name] = False
        self.model_patterns[name] = tuple(patterns)
        if cost is not None:
            self.scheduler.set_cost(name, cost)

    async def submit_job(
        self, model: str, n_queries: int, timeout: float = 20.0, retries: int = 3
    ) -> int:
        """`submit-job <model> <N>`: returns the job id. Await
        `wait_job(job_id)` for completion.

        The request carries an idempotency token and is retried on
        timeout (the transport is at-most-once UDP); the coordinator
        dedups by token so a retry can't mint a second job."""
        model = self._canon(model)
        token = self.node.new_rid()
        reply = await leader_retry(
            self.node,
            MsgType.SUBMIT_JOB_REQUEST,
            {"model": model, "n": int(n_queries), "token": token},
            timeout=timeout,
            retries=retries,
        )
        if not reply.get("ok"):
            raise RuntimeError(f"submit-job failed: {reply.get('error')}")
        job_id = int(reply["job_id"])
        self._job_done.setdefault(
            job_id, asyncio.get_running_loop().create_future()
        )
        return job_id

    async def wait_job(self, job_id: int, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Wait for completion. Primary signal is the coordinator's
        SUBMIT_JOB_REQUEST_SUCCESS push; because that is a single
        unacked datagram, we also poll job status as a fallback so a
        dropped notification (or a failover) can't strand the caller."""
        fut = self._job_done.setdefault(
            job_id, asyncio.get_running_loop().create_future()
        )

        async def waiter() -> Dict[str, Any]:
            unknown = 0
            while not fut.done():
                try:
                    return await asyncio.wait_for(asyncio.shield(fut), 1.0)
                except asyncio.TimeoutError:
                    try:
                        reply = await self.node.leader_request(
                            MsgType.JOB_STATUS_REQUEST, {"job": job_id}, timeout=2.0
                        )
                    except Exception:
                        continue
                    if reply.get("done") and not fut.done():
                        fut.set_result(dict(reply))
                    elif not reply.get("ok"):
                        # the (possibly newly-elected) coordinator has no
                        # record of this job: the standby relay was lost
                        # before the failover. Surface it instead of
                        # polling forever; the caller resubmits.
                        unknown += 1
                        if unknown >= 5:
                            raise RuntimeError(
                                f"job {job_id} lost (coordinator has no record; "
                                "resubmit)"
                            )
                    else:
                        unknown = 0
            return fut.result()

        try:
            result = await asyncio.wait_for(waiter(), timeout)
            if result.get("error"):
                raise RuntimeError(
                    f"job {job_id} failed: {result['error']}"
                )
            return result
        finally:
            if fut.done():
                self._job_done.pop(job_id, None)

    async def get_output(self, job_id: int, dest_path: str) -> Dict[str, Any]:
        """`get-output <jobid>`: collect every worker's
        output_<job>_<batch>_<host>.json from the store and merge into
        final_<jobid>.json (reference get_output_cli +
        merge_all_json_files, worker.py:1513-1534, 1617-1627)."""
        listing = await self.store.ls_all(f"output_{job_id}_*.json")
        merged: Dict[str, Any] = {}
        tmpdir = self.store.cfg.download_path()
        os.makedirs(tmpdir, exist_ok=True)
        for name in sorted(listing):
            local = os.path.join(tmpdir, name)
            await self.store.get(name, local)
            with open(local) as f:
                part = json.load(f)
            for k, v in part.items():
                merged.setdefault(k, v)
        dest_path = os.path.abspath(os.path.expanduser(dest_path))
        os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
        with open(dest_path, "w") as f:
            json.dump(merged, f, indent=2)
        return merged

    async def predict_locally(self, model: str, files: List[str]) -> Dict[str, Any]:
        """`predict-locally <model> <files...>` (reference
        worker.py:1573-1585): run inference on this node, no cluster."""
        model = self._canon(model)
        be = self._extra_backends.get(model, self._backend)
        results, exec_time, _ = await be(model, files)
        return {"results": results, "exec_time": exec_time}

    async def set_batch_size(self, model: str, batch_size: int) -> None:
        """C3 verb: cluster-wide batch size change (reference
        SET_BATCH_SIZE, worker.py:1028-1037)."""
        reply = await self.node.leader_request(
            MsgType.SET_BATCH_SIZE,
            {"model": self._canon(model), "batch_size": int(batch_size)},
        )
        # the ACK's ok flag gates success (drift-wire-payloads: it was
        # shipped but never checked — a garbled rid-resolved reply
        # passed as a silent success)
        if not reply.get("ok"):
            raise RuntimeError(f"set-batch-size {model} not acknowledged")

    async def c2_stats(self, model: str) -> Dict[str, float]:
        """C2: processing-time stats, computed on the coordinator,
        fetchable from any node (reference GET_C2_COMMAND,
        worker.py:1039-1059)."""
        reply = await self.node.leader_request(
            MsgType.GET_C2_COMMAND, {"model": self._canon(model)}
        )
        if not reply.get("ok"):
            raise RuntimeError(f"c2-stats {model} not acknowledged")
        return reply.get("stats", {})

    def c1_stats(self) -> Dict[str, Dict[str, float]]:
        """C1 is local to the coordinator; non-coordinators show their
        shadow counts (reference prints on the leader)."""
        return self.scheduler.c1_stats()

    def c5_assignments(self) -> Dict[str, Any]:
        return self.scheduler.c5_assignments()

    @property
    def pipeline_depth(self) -> int:
        """Worker-pipelining depth (operator surface; the scheduler
        owns the knob)."""
        return self.scheduler.pipeline_depth

    def set_pipeline_depth(self, depth: Optional[int]) -> None:
        """`None` → adaptive (probe-and-commit DepthController, the
        product default); an int → static depth, controller off (the
        bench's forced-comparison runs and reference-faithful depth-1
        use this)."""
        if depth is None:
            self.depth_ctl = DepthController()
            self.scheduler.pipeline_depth = self.depth_ctl.depth
        else:
            self.depth_ctl = None
            self.scheduler.pipeline_depth = max(1, int(depth))

    def depth_controller_stats(self) -> Dict[str, Any]:
        """CLI `breakdown`: the depth in force and WHY (probe rates,
        trigger, drift signature) — or the pinned static depth."""
        if self.depth_ctl is None:
            return {
                "mode": "static", "depth": self.scheduler.pipeline_depth,
            }
        out = {"mode": "adaptive", **self.depth_ctl.explain()}
        # analytic prior next to the measurement: the upper bound on
        # what depth-2 overlap COULD buy given the current stage walls
        bd = self.breakdown_stats()
        if bd:
            out["overlap_headroom_bound"] = overlap_headroom(
                fetch_s=bd.get("fetch_ms", 0.0) / 1e3,
                decode_s=bd.get("decode_ms", 0.0) / 1e3,
                infer_s=bd.get("infer_ms", 0.0) / 1e3,
                put_s=bd.get("put_ms", 0.0) / 1e3,
            )
        return out

    def decode_cache_stats(self) -> Dict[str, int]:
        """Worker decoded-input cache counters (operator surface for
        the CLI `breakdown` verb)."""
        return {
            "hits": self.decode_cache_hits,
            "misses": self.decode_cache_misses,
            "bytes_used": self._decode_cache_used,
            "bytes_budget": self.decode_cache_bytes,
        }

    def breakdown_stats(self) -> Dict[str, float]:
        """Mean per-batch wall-time split from ACK-carried timings
        (coordinator-side; VERDICT r2 item 9, stages named fully per
        r4 item 4): `fetch_ms` replica fetch, `decode_ms` host JPEG
        decode (backend − infer), `infer_ms` the engine's infer call —
        device forward PLUS dispatch, which on a remoted chip is
        dominated by the tunnel round-trips (device compute for a b32
        ResNet batch is ~2.2 ms; see the bench sweep) —
        `stage_wait_ms` the time a STAGED batch sat parked, prepare
        done, waiting out the previous batch's inference (pipelining
        means this stage runs CONCURRENTLY with another batch's
        infer — it is exec-accounting, not lost wall time), `put_ms`
        the output write + replicated store PUT, and `other_ms` the
        unattributed residue (result re-keying, ACK send, loop
        scheduling; should be near zero). Per-batch exec
        sums across stages while the job's WALL tracks max(stage) —
        overlap means the sum exceeds wall. Empty dict when no
        samples."""
        if not self.batch_timing:
            return {}
        n = len(self.batch_timing)
        mean = lambda k: sum(s.get(k, 0.0) for s in self.batch_timing) / n  # noqa: E731
        f, b, i, e = mean("fetch"), mean("backend"), mean("infer"), mean("exec")
        sw, p = mean("stage_wait"), mean("put")
        return {
            "batches": n,
            "fetch_ms": round(f * 1e3, 1),
            "decode_ms": round((b - i) * 1e3, 1),
            "infer_ms": round(i * 1e3, 1),
            "stage_wait_ms": round(sw * 1e3, 1),
            "put_ms": round(p * 1e3, 1),
            "other_ms": round((e - f - b - sw - p) * 1e3, 1),
            "exec_ms": round(e * 1e3, 1),
        }

    # ------------------------------------------------------------------
    # handler registration
    # ------------------------------------------------------------------

    def _register(self) -> None:
        n = self.node
        n.register(MsgType.SUBMIT_JOB_REQUEST, self._h_submit_job)
        n.register(MsgType.SUBMIT_JOB_REQUEST_SUCCESS, self._h_job_success)
        n.register(MsgType.SUBMIT_JOB_RELAY, self._h_submit_relay)
        n.register(MsgType.JOBS_RESTORE_RELAY, self._h_restore_relay)
        n.register(MsgType.JOB_FAILED_RELAY, self._h_job_failed_relay)
        n.register(MsgType.WORKER_TASK_REQUEST, self._h_task_request)
        n.register(MsgType.WORKER_STAGE_CANCEL, self._h_stage_cancel)
        n.register(MsgType.WORKER_TASK_REQUEST_ACK, self._h_task_ack)
        n.register(MsgType.WORKER_TASK_FAIL, self._h_task_fail)
        n.register(MsgType.WORKER_TASK_ACK_RELAY, self._h_ack_relay)
        n.register(MsgType.LM_PREFILL_REQUEST, self._h_lm_prefill)
        n.register(MsgType.SET_BATCH_SIZE, self._h_set_batch_size)
        n.register(MsgType.GET_C2_COMMAND, self._h_get_c2)
        n.register(MsgType.JOB_STATUS_REQUEST, self._h_job_status)

    # ------------------------------------------------------------------
    # coordinator side
    # ------------------------------------------------------------------

    async def _schedule_loop(self) -> None:
        """Periodic scheduling tick: catches workers that joined after
        the last event-driven round (the reference reschedules only on
        ACKs, worker.py:1025-1026, so late joiners idle until one)."""
        interval = max(self.node.spec.timing.ping_interval, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                if self.node.is_leader:
                    self._run_schedule()
                    self._resend_stale_assignments()
            except Exception:
                log.exception("%s: scheduling tick failed", self._me)

    def _run_schedule(self) -> None:
        # worker_pool() collapses formed groups and refreshes
        # _pool_weights; the DepthController below operates at the
        # same granularity — a group is one slot, its probe ACKs all
        # arrive under the primary's name
        pool = self.worker_pool()
        if self.depth_ctl is not None:
            # elastic membership: a join/leave that changed the slot
            # count counts as drift — the committed pipelining depth
            # re-validates against the pool that exists NOW
            self.depth_ctl.on_pool_size(len(pool))
            queued = sum(len(q) for q in self.scheduler.queues.values())
            self.scheduler.pipeline_depth = self.depth_ctl.tick(queued)
        assigns = self.scheduler.schedule(
            pool, weights=self._pool_weights
        )
        for w, key in self.scheduler.pop_revoked_stages():
            sat = self._staged_at.get(w)
            if sat is not None and sat[0] == key:
                del self._staged_at[w]
            self.node.send_unique(
                w, MsgType.WORKER_STAGE_CANCEL,
                {"job": key[0], "batch": key[1],
                 "seq": next(self._task_seq), "inc": self._incarnation},
            )
        for a in assigns:
            self._send_task(a.worker, a.batch, staged=a.staged)

    def _resend_stale_assignments(self) -> None:
        """Re-send assignments in flight past the resend deadline: the
        request or its ACK may have been dropped (SWIM's reliability
        pattern applied to the task channel)."""
        now = time.monotonic()
        for worker, batch in list(self.scheduler.in_progress.items()):
            key_t = self._assigned_at.get(worker)
            if key_t is None or key_t[0] != batch.key:
                self._send_task(worker, batch)
            elif now - key_t[1] > self.task_resend_after:
                log.info(
                    "%s: re-sending un-ACKed batch %s to %s",
                    self._me, batch.key, worker,
                )
                self._send_task(worker, batch)
        for worker, batch in list(self.scheduler.prefetch.items()):
            key_t = self._staged_at.get(worker)
            if (
                key_t is None
                or key_t[0] != batch.key
                or now - key_t[1] > self.task_resend_after
            ):
                self._send_task(worker, batch, staged=True)

    def _send_task(self, worker: str, b: Batch, staged: bool = False) -> None:
        # replicas are resolved at send time from the live metadata so
        # re-replication and failover promotions are reflected
        # (reference resolves at assignment, worker.py:290-297)
        versions: Dict[str, int] = {}
        if self.node.is_leader:
            for f in set(b.files):
                reps = self.store.metadata.replicas_of(f)
                if reps:
                    b.replicas[f] = reps
                versions[f] = self.store.metadata.latest_version(f)
        if staged:
            self._staged_at[worker] = (b.key, time.monotonic())
        else:
            self._assigned_at[worker] = (b.key, time.monotonic())
            if b.traces:
                # close the scheduler-side `dispatch` span on the
                # FIRST real send: `q` (stamped by the router at
                # ingress_submit) -> now covers scheduler queue wait +
                # assignment. Popping `q` keeps resends from minting
                # duplicate spans.
                now_wall = time.time()
                for e in b.traces:
                    q = e.pop("q", None) if isinstance(e, dict) else None
                    if q is None:
                        continue
                    ctx = TraceContext.from_wire(e)
                    if ctx is not None and ctx.sampled:
                        TRACER.start_span(
                            "dispatch", ctx=ctx, node=self._me,
                            t0=float(q),
                            labels={"worker": worker, "job": b.job_id,
                                    "batch": b.batch_id},
                        ).end(now_wall)
        try:
            self.node.send_unique(
                worker,
                MsgType.WORKER_TASK_REQUEST,
                {
                    "job": b.job_id,
                    "batch": b.batch_id,
                    "model": b.model,
                    "files": b.files,
                    "replicas": b.replicas,
                    "versions": versions,
                    "staged": staged,
                    "streams": b.streams,
                    "inline": b.inline_results,
                    "traces": b.traces,
                    "seq": next(self._task_seq),
                    "inc": self._incarnation,
                },
            )
        except Exception:
            # oversized/failed frame: leave in_progress; the resend
            # tick will retry and the failure is visible in the log
            log.exception("%s: sending batch %s to %s failed", self._me, b.key, worker)

    async def _h_submit_job(self, msg: Message, addr) -> None:
        """Intake (reference SUBMIT_JOB_REQUEST, worker.py:911-920):
        mint the id, batch the queries, relay to the standby, ACK the
        client, schedule."""
        if not self.node.is_leader:
            return
        rid = msg.data.get("rid")
        token = msg.data.get("token")
        if token and token in self._submit_tokens:
            # duplicate of a submit whose ACK was lost: re-ACK, same id
            self.node.send_unique(
                msg.sender,
                MsgType.SUBMIT_JOB_REQUEST_ACK,
                {"rid": rid, "ok": True, "job_id": self._submit_tokens[token]},
            )
            return
        model = msg.data.get("model", "")
        n = int(msg.data.get("n", 0))
        # case-insensitive like _canon: the submitting node may have
        # registered a different casing than the leader
        lm_hit = {k.lower(): k for k in self.model_patterns}.get(model.lower())
        if lm_hit is not None:
            model = lm_hit
            patterns = self.model_patterns[lm_hit]
            known = True
        else:
            # only registry CNNs may take the image-pattern default; an
            # LM whose register_lm was skipped on the leader must fail
            # fast here, not burn max_batch_failures on *.jpeg batches
            patterns = self.image_patterns
            try:
                get_model(model)
                known = True
            except KeyError:
                known = False
        error = None
        if not known:
            error = (
                f"model {model!r} is neither a registry CNN nor "
                "registered via register_lm on the leader; register it "
                "on every node (including the leader) before submitting"
            )
        elif n <= 0:
            error = f"n_queries must be positive, got {n}"
        files: list = []
        if error is None:
            files = sorted({
                f for p in patterns for f in self.store.metadata.matching(p)
            })
            if not files:
                error = f"no {'/'.join(patterns)} files in the store"
        if error is not None:
            self.node.send_unique(
                msg.sender,
                MsgType.SUBMIT_JOB_REQUEST_ACK,
                {"rid": rid, "ok": False, "error": error},
            )
            return
        job_id = self.scheduler.next_job_id()
        if token:
            self._submit_tokens[token] = job_id
        bs = self.scheduler.batch_size_of(model)
        replicas = {f: self.store.metadata.replicas_of(f) for f in files}
        self.scheduler.submit_job(
            job_id, model, files, n, msg.sender, replicas, batch_size=bs
        )
        # client ACK first: a relay failure must never eat the ACK
        self.node.send_unique(
            msg.sender,
            MsgType.SUBMIT_JOB_REQUEST_ACK,
            {"rid": rid, "ok": True, "job_id": job_id},
        )
        self._relay_submit(
            job_id,
            {"job": job_id, "model": model, "n": n, "files": files,
             "batch_size": bs, "requester": msg.sender,
             "gen": self._relay_gen},
        )
        self._run_schedule()

    def _relay_submit(self, job_id: int, payload: Dict[str, Any]) -> None:
        """One copy of the standby submit-relay discipline (operator
        and ingress intake both use it): slim relay — file names + the
        exact batch_size used for slicing (so shadow batch ids always
        match); replicas are re-resolved from metadata at promotion
        time. A relay failure is logged, never raised (the client ACK
        must already be out)."""
        sb = self.store.standby_node()
        if sb is not None and sb.unique_name != self._me:
            try:
                self.node.send(sb, MsgType.SUBMIT_JOB_RELAY, payload)
            except Exception:
                log.exception(
                    "%s: standby relay of job %d failed", self._me, job_id
                )

    def ingress_submit(
        self,
        job_id: int,
        model: str,
        files: List[str],
        requester: str,
        affinity: Optional[str] = None,
        streams: Optional[Dict[str, List[Any]]] = None,
        slo_class: Optional[str] = None,
        traces: Optional[List[Dict[str, Any]]] = None,
    ) -> Any:
        """Leader-side direct intake for the request front door
        (dml_tpu/ingress/router.py): a batch the router FORMED from
        individual requests becomes one single-batch job — explicit
        file list, n = len(files), batch_size pinned to the formed
        size — and inherits the whole job pipeline: fair-share
        scheduling against operator jobs, standby relays, exactly-once
        completion dedup, requeue on worker death, failover.

        `affinity` is the batch's session-affinity target (the worker
        holding its sessions' KV state); `streams` maps input files of
        streaming requests to a LIST of [client, request id] targets
        (several requests may share one input) so the executing
        worker can expose per-request token streams. Both relay to
        the standby so a promoted coordinator re-sends identically."""
        if not self.node.is_leader:
            raise RuntimeError("ingress_submit runs on the coordinator")
        if not files:
            raise ValueError("empty ingress batch")
        replicas = {
            f: self.store.metadata.replicas_of(f) for f in set(files)
        }
        st = self.scheduler.submit_job(
            job_id, model, list(files), len(files), requester, replicas,
            batch_size=len(files), affinity=affinity, streams=streams,
            inline_results=True, slo_class=slo_class, traces=traces,
        )
        self._relay_submit(
            job_id,
            {"job": job_id, "model": model, "n": len(files),
             "files": list(files), "batch_size": len(files),
             "requester": requester, "gen": self._relay_gen,
             "affinity": affinity, "streams": streams or {},
             "inline": True, "slo": slo_class,
             "traces": traces or []},
        )
        self._run_schedule()
        return st

    async def _h_task_ack(self, msg: Message, addr) -> None:
        """A worker finished a batch (reference WORKER_TASK_REQUEST_ACK
        handler, worker.py:989-1026)."""
        if not self.node.is_leader:
            return
        d = msg.data
        job_id, batch_id = int(d["job"]), int(d["batch"])
        _M_ACKS.inc(model=d.get("model", ""))
        cost = d.get("cost")
        if cost:
            self._fold_cost(d.get("model", ""), cost)
        at = self._assigned_at.get(msg.sender)
        if at is not None and at[0] == (job_id, batch_id):
            # the cross-check's unforgeable side: OUR wall between
            # dispatch and this ACK, paired with the worker's self-
            # reported exec wall inside the payload
            self.signal.observe_ack(
                msg.sender, time.monotonic() - at[1], d
            )
            del self._assigned_at[msg.sender]
        sat = self._staged_at.get(msg.sender)
        if sat is not None and sat[0] == (job_id, batch_id):
            del self._staged_at[msg.sender]
        # freshness BEFORE on_batch_done marks it complete: the depth
        # controller must see each batch exactly once (a duplicated
        # ACK — LinkShaper dup injection, re-ACK of a resent task —
        # counted into a probe phase would inflate that phase's rate
        # and could flip the commit)
        st_pre = self.scheduler.jobs.get(job_id)
        fresh_ack = (
            st_pre is not None
            and batch_id not in st_pre.completed_batches
        )
        if fresh_ack and isinstance(d.get("results"), dict):
            # inline-results (ingress) batch: the results rode the ACK
            # instead of the store; merge across the job's batches so
            # the completion observers can fan them out per request
            st_pre.inline_results = {
                **(st_pre.inline_results or {}), **d["results"],
            }
        if fresh_ack and "fetch_time" in d:
            # ACK-carried stage walls, kept on the job state: the
            # request front door's terminal attribution (per-request
            # `stages` + the deadline-miss stage= counter) reads these
            # synchronously at completion — available on a real
            # multi-process cluster where the worker's spans are not
            st_pre.stage_timing = {
                "fetch": float(d.get("fetch_time", 0.0)),
                "backend": float(d.get("backend_time", 0.0)),
                "infer": float(d.get("infer_time", 0.0)),
                "put": float(d.get("put_time", 0.0)),
                "exec": float(d.get("exec_time", 0.0)),
                "stage_wait": float(d.get("stage_wait_time", 0.0)),
            }
        if fresh_ack:
            # group-served ACKs advertise membership + capacity: this
            # is how any coordinator — including one promoted mid-job
            # — learns measured group capacity for the fair-share
            # weights. FRESH acks only: a duplicate/stale delivery
            # must not revert the capacity any more than it may feed
            # the scheduler counts or the DepthController below.
            self.groups.observe_ack(msg.sender, d)
        done = self.scheduler.on_batch_done(
            msg.sender, job_id, batch_id,
            float(d.get("exec_time", 0.0)), int(d.get("n_images", 0)),
        )
        # promotion bookkeeping: the worker moved on to its staged
        # batch when this one finished — carry the stage's send time
        # over so the resend loop doesn't immediately re-send it
        cur = self.scheduler.in_progress.get(msg.sender)
        sat = self._staged_at.get(msg.sender)
        if cur is not None and sat is not None and sat[0] == cur.key:
            self._assigned_at[msg.sender] = sat
            del self._staged_at[msg.sender]
        if self.depth_ctl is not None and fresh_ack:
            # adaptive depth: fold the ACK (and its stage walls) into
            # the probe/drift machinery and apply what it decides
            self.scheduler.pipeline_depth = self.depth_ctl.on_ack(
                int(d.get("n_images", 0)),
                fetch=float(d.get("fetch_time", 0.0)),
                infer=float(d.get("infer_time", 0.0)),
                put=float(d.get("put_time", 0.0)),
                worker=msg.sender,
            )
        if "fetch_time" in d:
            self.batch_timing.append({
                "model": d.get("model", ""),
                "exec": float(d.get("exec_time", 0.0)),
                "fetch": float(d.get("fetch_time", 0.0)),
                "backend": float(d.get("backend_time", 0.0)),
                "infer": float(d.get("infer_time", 0.0)),
                "stage_wait": float(d.get("stage_wait_time", 0.0)),
                "put": float(d.get("put_time", 0.0)),
                "n": int(d.get("n_images", 0)),
            })
        sb = self.store.standby_node()
        if sb is not None and sb.unique_name != self._me:
            self.node.send(
                sb,
                MsgType.WORKER_TASK_ACK_RELAY,
                {"job": job_id, "batch": batch_id,
                 "n_images": int(d.get("n_images", 0)),
                 "gen": self._relay_gen},
            )
        if done is not None:
            self.node.send_unique(
                done.requester,
                MsgType.SUBMIT_JOB_REQUEST_SUCCESS,
                {"job_id": job_id, "model": done.model,
                 "total_queries": done.total_queries},
            )
            self._fire_job_done(done, msg.sender)
        self._run_schedule()

    def _fold_cost(self, model: str, cost: Dict[str, Any]) -> None:
        """Adopt device-measured constants (replaces the reference's
        hardcoded CPU numbers, worker.py:57-89)."""
        cur = self.scheduler.costs.get(model)
        if cur is None:
            return
        self.scheduler.costs[model] = cur.with_measurements(
            load_time=cost.get("load_time"),
            first_query=cost.get("first_query"),
            per_query=cost.get("per_query"),
        )

    async def _h_lm_prefill(self, msg: Message, addr) -> None:
        """Prefill-role worker side of disaggregated LM serving: a
        decode primary sent a batch's prompt token ids; run the
        chunked prefill (LMPrefillBackend) and hand the slabs back
        over the data plane. Two forms:

        - ``stream: true`` (the chunk-streamed handoff): ACK a LIVE
          stream token IMMEDIATELY, then push each request's framed
          slab chunks as its prefill completes — the decode side
          adopts early requests while later ones still compute.
        - default: the whole-slab file token (PR-6 form, kept as the
          bench's comparison baseline and for old-form callers).

        The prefill runs as a background task — blocking the receive
        loop on a device forward would stall SWIM heartbeats into
        false suspicion (same discipline as the shadow-restore
        fetch)."""
        d = msg.data
        rid = d.get("rid")
        model = str(d.get("model", ""))
        pf = self._lm_prefill.get(model)
        if pf is None:
            self.node.send_unique(
                msg.sender, MsgType.LM_PREFILL_ACK,
                {"rid": rid, "ok": False,
                 "error": f"no prefill backend for {model!r} on "
                          f"{self._me}"},
            )
            return
        prompts = d.get("prompts") or []
        budgets = d.get("budgets") or []
        # remote-draft speculation: the decode primary asks for this
        # many draft tokens per slab; a backend without a draft model
        # (or an old one without the parameter) just omits them
        draft_k = int(d.get("draft_k") or 0)
        # per-request trace contexts shipped by the decode primary:
        # the prefill member records its own `prefill` span per
        # sampled request so the stitched trace shows where the
        # disaggregated context phase ran
        pf_ctxs = [
            c for e in (d.get("traces") or [])
            if (c := TraceContext.from_wire(e)) is not None and c.sampled
        ]

        def _prefill_spans(t0_wall: float) -> None:
            t1_wall = time.time()
            for c in pf_ctxs:
                TRACER.start_span(
                    "prefill", ctx=c, node=self._me, t0=t0_wall,
                    labels={"model": model, "shared": len(prompts)},
                ).end(t1_wall)

        if d.get("stream") and hasattr(pf, "stream_slabs"):
            dp = self.store.data_plane
            # small buffer bound: the slab producer pushes via the
            # backpressured put(), so this caps in-flight memory per
            # handoff instead of buffering a whole share's slabs
            token, feed = dp.expose_stream(maxsize=64)

            async def serve_stream() -> None:
                t0_wall = time.time()
                try:
                    if draft_k > 0:
                        await pf.stream_slabs(
                            prompts, budgets, feed, draft_k=draft_k
                        )
                    else:
                        # positional form: older/stub prefill backends
                        # predate the draft_k parameter
                        await pf.stream_slabs(prompts, budgets, feed)
                    _prefill_spans(t0_wall)
                finally:
                    # unexpose the moment the puller drains to EOF;
                    # the TTL only bounds leakage when the puller
                    # died mid-handoff and never comes back
                    deadline = time.monotonic() + 120.0
                    while (not feed.drained()
                           and time.monotonic() < deadline):
                        await asyncio.sleep(0.5)
                    dp.unexpose_stream(token)

            self._spawn_bg(
                serve_stream(),
                f"lm prefill stream {model} x{len(prompts)}",
            )
            self.node.send_unique(
                msg.sender, MsgType.LM_PREFILL_ACK,
                {"rid": rid, "ok": True, "token": token,
                 "stream": True, "n": len(prompts)},
            )
            return
        self._spawn_bg(
            self._serve_prefill(
                pf, prompts, budgets, msg.sender, rid, _prefill_spans,
                draft_k=draft_k,
            ),
            f"lm prefill {model} x{len(prompts)}",
        )

    async def _serve_prefill(
        self, pf, prompts, budgets, reply_to: str, rid,
        prefill_spans=None, draft_k: int = 0,
    ) -> None:
        import tempfile

        try:
            t0_wall = time.time()
            if draft_k > 0:
                data = await asyncio.to_thread(
                    pf.slabs_bytes, prompts, budgets, draft_k
                )
            else:
                data = await asyncio.to_thread(
                    pf.slabs_bytes, prompts, budgets
                )
            if prefill_spans is not None:
                prefill_spans(t0_wall)
            tmpdir = self.store.cfg.download_path()
            os.makedirs(tmpdir, exist_ok=True)
            fd, path = tempfile.mkstemp(prefix="kvslab_", dir=tmpdir)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            token = self.store.data_plane.expose(path)

            async def cleanup() -> None:
                # the decode side pulls exactly once, promptly; the
                # TTL bounds leakage when it died mid-handoff
                await asyncio.sleep(120.0)
                self.store.data_plane.unexpose(token)
                try:
                    os.unlink(path)
                except OSError:
                    pass

            self._spawn_bg(cleanup(), f"kv-slab ttl {token[:8]}")
            self.node.send_unique(
                reply_to, MsgType.LM_PREFILL_ACK,
                {"rid": rid, "ok": True, "token": token,
                 "size": len(data), "n": len(prompts)},
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.exception("%s: prefill slab build failed", self._me)
            self.node.send_unique(
                reply_to, MsgType.LM_PREFILL_ACK,
                {"rid": rid, "ok": False, "error": str(e)},
            )

    async def _h_set_batch_size(self, msg: Message, addr) -> None:
        """C3: leader updates the scheduler and fans out to every live
        node so engines recompile at the new shape."""
        model = msg.data["model"]
        bs = int(msg.data["batch_size"])
        if msg.data.get("fanout"):
            # every node updates its scheduler too, so a standby
            # promoted later batches new jobs at the current C3 setting
            self._apply_batch_size(model, bs)
            return
        if not self.node.is_leader:
            return
        self._apply_batch_size(model, bs)
        for node in self.node.membership.alive_nodes():
            if node.unique_name != self._me:
                self.node.send(
                    node, MsgType.SET_BATCH_SIZE,
                    {"model": model, "batch_size": bs, "fanout": True},
                )
        # reply type is unregistered, so the client dispatcher's
        # fallback resolves the awaiting rid future
        self.node.send_unique(
            msg.sender, MsgType.SET_BATCH_SIZE_ACK,
            {"rid": msg.data.get("rid"), "ok": True},
        )

    def _apply_batch_size(self, model: str, bs: int) -> None:
        try:
            self.scheduler.set_batch_size(model, bs)
        except KeyError:
            pass
        eng = self._engine
        if eng is not None and model in eng.loaded_models:
            # the engine-side reshape warms up (compile + 2 forwards)
            # — minutes through a remoted chip, so NEVER on the event
            # loop (it would stall SWIM heartbeats into false
            # suspicion and time out the C3 RPC). The scheduler's
            # batch size above switches immediately; engine-side the
            # new chunk shape takes effect at once (compiling lazily
            # on first use) while in-flight nowait handles keep their
            # dispatch-time size snapshot (engine._dispatch_chunk).
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                eng.set_batch_size(model, bs)
            else:
                self._spawn_bg(
                    asyncio.to_thread(eng.set_batch_size, model, bs),
                    f"batch-size warmup {model}@{bs}",
                )

    async def _h_job_status(self, msg: Message, addr) -> None:
        """Pull-based completion fallback (no reference equivalent —
        the reference's single completion datagram can strand clients;
        this closes that gap)."""
        if not self.node.is_leader:
            return
        st = self.scheduler.job_state(int(msg.data.get("job", -1)))
        self.node.send_unique(
            msg.sender,
            MsgType.JOB_STATUS_ACK,
            {
                "rid": msg.data.get("rid"),
                "ok": st is not None,
                "done": bool(st and st.done),
                "job_id": st.job_id if st else None,
                "model": st.model if st else None,
                "total_queries": st.total_queries if st else 0,
                "error": st.error if st else None,
            },
        )

    async def _h_get_c2(self, msg: Message, addr) -> None:
        if not self.node.is_leader:
            return
        self.node.send_unique(
            msg.sender,
            MsgType.GET_C2_COMMAND_ACK,
            {"rid": msg.data.get("rid"), "ok": True,
             "stats": self.scheduler.c2_stats(msg.data.get("model", ""))},
        )

    async def _h_task_fail(self, msg: Message, addr) -> None:
        """A live worker could not run its batch (e.g. an input had no
        reachable replica): requeue it and free the worker — without
        this the worker would sit 'busy' forever and the job would
        hang."""
        if not self.node.is_leader:
            return
        failed_key = (int(msg.data["job"]), int(msg.data["batch"]))
        at = self._assigned_at.get(msg.sender)
        if at is not None and at[0] == failed_key:
            del self._assigned_at[msg.sender]
        sat = self._staged_at.get(msg.sender)
        if sat is not None and sat[0] == failed_key:
            del self._staged_at[msg.sender]
        b = self.scheduler.on_batch_failed(msg.sender, *failed_key)
        # a failed PRIMARY promotes the worker's staged batch (the
        # worker does the same) — carry the stage's send time over
        cur = self.scheduler.in_progress.get(msg.sender)
        sat = self._staged_at.get(msg.sender)
        if cur is not None and sat is not None and sat[0] == cur.key:
            self._assigned_at[msg.sender] = sat
            del self._staged_at[msg.sender]
        if b is not None:
            log.info(
                "%s: batch %s failed on %s (%s); requeued",
                self._me, b.key, msg.sender, msg.data.get("error"),
            )
        for st in self.scheduler.pop_failed_jobs():
            # the batch hit the failure cap: fail the JOB loudly (the
            # alternative is an infinite fail/requeue loop pinning a
            # worker while the client waits forever)
            log.error("%s: job %d FAILED: %s", self._me, st.job_id, st.error)
            self.node.send_unique(
                st.requester,
                MsgType.SUBMIT_JOB_REQUEST_SUCCESS,
                {"job_id": st.job_id, "model": st.model,
                 "total_queries": st.total_queries, "error": st.error},
            )
            # the standby's shadow must drop the job too, or a
            # failover resurrects work the client was told failed
            sb = self.store.standby_node()
            if sb is not None and sb.unique_name != self._me:
                self.node.send(
                    sb, MsgType.JOB_FAILED_RELAY,
                    {"job": st.job_id, "error": st.error,
                     "gen": self._relay_gen},
                )
            self._fire_job_done(st, None)
        self._run_schedule()

    def _fire_job_done(self, st, worker: Optional[str]) -> None:
        """Notify job-terminal observers (ingress completion fan-out);
        a broken observer must never break the ACK path."""
        for cb in self.on_job_done_cbs:
            try:
                cb(st, worker)
            except Exception:
                log.exception("%s: on_job_done callback failed", self._me)

    def _on_node_failed(self, uname: str) -> None:
        """Requeue the dead worker's batch and reschedule (reference
        handle_failures_if_pending_status, worker.py:1279-1306).

        Group degradation is handled here too. The directory edge is
        acted on by the COORDINATOR (worker-side serving decisions —
        group_role, member liveness checks around the device call —
        are computed live, not from the edge), and the requeue of the
        group primary's in-flight batches is its job: those batches were
        executing on an ICI domain that no longer exists, so they go
        back to the queue front like a dead worker's, even though the
        primary node itself is alive. If the primary does manage to
        ACK the old batch (the sim's stub mesh has no real ICI to
        lose), completion dedup counts it exactly once and the
        requeued copy's late ACK is dropped the same way."""
        degraded = self.groups.on_node_failed(uname)
        if not self.node.is_leader:
            return
        self._assigned_at.pop(uname, None)
        self._staged_at.pop(uname, None)
        if self.scheduler.on_worker_failed(uname) is not None:
            log.info("%s: requeued batch from dead worker %s", self._me, uname)
        if degraded is not None:
            gname, primary = degraded
            if primary != uname:
                self._assigned_at.pop(primary, None)
                self._staged_at.pop(primary, None)
                # had_work BEFORE the call: on_worker_failed requeues
                # the staged (prefetch) batch too but only RETURNS the
                # in-progress one, and a staged-only requeue must
                # still be counted and logged
                had_work = (
                    primary in self.scheduler.in_progress
                    or primary in self.scheduler.prefetch
                )
                self.scheduler.on_worker_failed(primary)
                if had_work:
                    note_group_requeue(gname)
                    log.info(
                        "%s: group %s degraded by %s death; requeued "
                        "primary %s's in-flight work onto the "
                        "reformed single-chip pool",
                        self._me, gname, uname, primary,
                    )
        self._run_schedule()

    def _on_became_leader(self) -> None:
        """Failover promotion (reference worker.py:577-588): the shadow
        queues built from relays become live; resume scheduling. Any
        batch the dead primary had in flight on a worker will be ACKed
        to us (workers ACK the *current* leader) or re-sent — shadow
        queues still hold every un-ACKed batch, so nothing is lost."""
        if self.scheduler.queue_depths():
            log.info(
                "%s: promoted to coordinator with shadow queues %s",
                self._me, self.scheduler.queue_depths(),
            )
        self._run_schedule()

    # ------------------------------------------------------------------
    # standby side (reference worker.py:887-897, 965-986)
    # ------------------------------------------------------------------

    def _gen_of(self, msg: Message) -> int:
        return int(msg.data.get("gen", 0))

    def _log_relay(self, entry: Tuple[str, int, Any, Message]) -> None:
        """Record a relay for post-restore replay. The bounded deque
        covers normal operation; while a restore is pending (across
        fetch retries) the unbounded side buffer guarantees nothing
        sent at/after the restore generation can be evicted before
        the replay runs."""
        self._relay_log.append(entry)
        if self._restore_buffer_gen is not None:
            self._restore_buffer.append(entry)

    def _gen_stale(self, msg: Message) -> bool:
        """A relay from the current leader with a generation below the
        last restored one reflects pre-restore state the coordinator
        deliberately wiped — drop it."""
        return (
            self._shadow_gen is not None
            and msg.sender == self._shadow_gen_leader
            and self._gen_of(msg) < self._shadow_gen
        )

    async def _h_submit_relay(self, msg: Message, addr) -> None:
        if msg.sender != self.node.leader_unique or self._gen_stale(msg):
            return
        # log first, then apply: if a snapshot restore is (or gets)
        # in flight, replaying the log after restore() re-applies
        # everything sent at/after the restore generation. Apply-fns
        # are idempotent, so apply-now + replay-later is always safe.
        self._log_relay(
            (msg.sender, self._gen_of(msg), self._apply_submit_relay, msg)
        )
        self._apply_submit_relay(msg)

    def _apply_submit_relay(self, msg: Message) -> None:
        d = msg.data
        job_id = int(d["job"])
        if self.scheduler.job_state(job_id) is not None:
            return
        self.scheduler.submit_job(
            job_id, d["model"], d["files"], int(d["n"]), d["requester"],
            batch_size=int(d["batch_size"]) if d.get("batch_size") else None,
            affinity=d.get("affinity"),
            streams=d.get("streams") or None,
            inline_results=bool(d.get("inline")),
            slo_class=d.get("slo"),
            traces=d.get("traces") or None,
        )

    async def _h_ack_relay(self, msg: Message, addr) -> None:
        if msg.sender != self.node.leader_unique or self._gen_stale(msg):
            return
        self._log_relay(
            (msg.sender, self._gen_of(msg), self._apply_ack_relay, msg)
        )
        self._apply_ack_relay(msg)

    def _apply_ack_relay(self, msg: Message) -> None:
        self.scheduler.shadow_prune(
            int(msg.data["job"]), int(msg.data["batch"]),
            int(msg.data.get("n_images", 0)),
        )

    async def _h_job_failed_relay(self, msg: Message, addr) -> None:
        if msg.sender != self.node.leader_unique or self._gen_stale(msg):
            return
        self._log_relay(
            (msg.sender, self._gen_of(msg), self._apply_job_failed_relay, msg)
        )
        self._apply_job_failed_relay(msg)

    def _apply_job_failed_relay(self, msg: Message) -> None:
        st = self.scheduler.fail_job(
            int(msg.data["job"]), str(msg.data.get("error", "failed"))
        )
        self.scheduler.pop_failed_jobs()  # shadow doesn't notify clients
        if st is not None:
            log.info(
                "%s: shadow dropped failed job %d", self._me, st.job_id
            )

    async def _h_restore_relay(self, msg: Message, addr) -> None:
        """Standby side of restore-jobs: pull the same pinned snapshot
        from the store and make it the shadow state, so a failover
        right after a restore loses nothing.

        The fetch runs as a task — awaiting a store GET inline would
        block this node's receive loop on a reply that loop itself must
        process (self-deadlock until timeout, plus a suspicion storm
        from unanswered pings). ACKs (echoing rid) go back only after a
        restore lands, so the coordinator's retry loop covers lost
        datagrams AND failed fetches. Duplicate restores are keyed by
        (leader, version, generation): a deliberate re-restore to the
        same version bumps the generation, so it re-applies."""
        if msg.sender != self.node.leader_unique or self.node.is_leader:
            return
        version = int(msg.data["version"])
        gen = self._gen_of(msg)
        rid = msg.data.get("rid")
        if self._restored_keys.get((msg.sender, version, gen)):
            if rid:  # duplicate/retry of a landed restore: ack only
                self.node.send_unique(
                    msg.sender, MsgType.JOBS_RESTORE_RELAY_ACK,
                    {"rid": rid, "ok": True},
                )
            return
        # monotonicity: a delayed/retried relay from an OLDER restore
        # must not roll the shadow back to an older snapshot. Ack it
        # (so its retry loop stops) without applying.
        if self._gen_stale(msg):
            if rid:
                self.node.send_unique(
                    msg.sender, MsgType.JOBS_RESTORE_RELAY_ACK,
                    {"rid": rid, "ok": True},
                )
            return
        # buffer scope = the whole restore of this generation: opened
        # the moment the generation is FIRST seen (even if an older
        # generation's fetch is still in flight — its replay won't
        # close a buffer that has moved past it), surviving failed
        # fetch attempts (the coordinator's resend re-enters here with
        # the same gen), and closed only by a successful replay of the
        # current buffer generation / promotion. A newer generation
        # supersedes the old buffer.
        if self._restore_buffer_gen is None or gen > self._restore_buffer_gen:
            self._restore_buffer.clear()
            self._restore_buffer_gen = gen
        if self._shadow_restoring:
            return  # a fetch is already in flight; the retry re-asks
        # set the latch HERE (not inside the task): a second restore
        # relay queued right behind this one must not spawn a
        # concurrent fetch
        self._shadow_restoring = True
        # tracked via _spawn_bg: stop() must be able to cancel a fetch
        # still in flight, and a failed restore must be logged, not
        # dropped as a never-retrieved task exception
        self._spawn_bg(
            self._restore_shadow(version, gen, rid, msg.sender),
            "shadow-restore",
        )

    async def _restore_shadow(
        self, version: int, gen: int, rid: Optional[str], reply_to: str
    ) -> None:
        """Fetch + apply the snapshot, then replay every logged relay
        sent at/after the restore generation — relays race the fetch
        (and even the restore relay itself) arbitrarily over UDP, and
        restore() replaces the shadow wholesale, so anything the
        coordinator sent after bumping the generation must be
        re-applied on top."""
        snap = None
        try:
            for attempt in range(3):  # local retry before the 10s resend
                try:
                    snap = json.loads(await self.store.get_bytes(
                        self.JOBS_CKPT_NAME, version=version
                    ))
                    break
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception(
                        "%s: standby snapshot fetch failed (attempt %d)",
                        self._me, attempt + 1,
                    )
                    await asyncio.sleep(0.2 * (attempt + 1))
        finally:
            self._shadow_restoring = False
        if snap is None:
            # no ack -> coordinator retries the relay; keep the side
            # buffer OPEN so relays landing between fetch attempts
            # stay protected from log eviction
            return
        if self.node.is_leader:
            # promoted mid-fetch: the live state must not be clobbered,
            # and a leader never restores a shadow — retire the buffer
            self._restore_buffer.clear()
            self._restore_buffer_gen = None
            return
        self.scheduler.restore(snap)
        self._shadow_gen = gen
        self._shadow_gen_leader = reply_to
        replayed = 0
        # bounded log first, then the in-flight side buffer: overlap
        # applies twice, which is safe (idempotent apply-fns) and
        # guarantees no eviction gap under relay floods
        for sender, g, apply_fn, m in (
            list(self._relay_log) + self._restore_buffer
        ):
            if sender == reply_to and g >= gen:
                apply_fn(m)
                replayed += 1
        # replay succeeded: close the buffer only if no NEWER restore
        # generation has started accumulating in the meantime
        if self._restore_buffer_gen is not None and gen >= self._restore_buffer_gen:
            self._restore_buffer.clear()
            self._restore_buffer_gen = None
        self._restored_keys[(reply_to, version, gen)] = True
        if rid:
            self.node.send_unique(
                reply_to, MsgType.JOBS_RESTORE_RELAY_ACK,
                {"rid": rid, "ok": True},
            )
        log.info(
            "%s: shadow restored from snapshot v%d gen %d (%d jobs, "
            "%d relays replayed)",
            self._me, version, gen, len(self.scheduler.jobs), replayed,
        )

    # ------------------------------------------------------------------
    # worker side (reference handle_worker_task_request,
    # worker.py:518-537, 940-962)
    # ------------------------------------------------------------------

    async def _h_task_request(self, msg: Message, addr) -> None:
        d = msg.data
        key = (int(d["job"]), int(d["batch"]))
        seq = int(d.get("seq", 0))
        inc = int(d.get("inc", 0))
        stale = False
        if seq:
            prev_inc, prev_seq = self._last_seq.get(msg.sender, (0, 0))
            stale = inc < prev_inc or (inc == prev_inc and seq <= prev_seq)
            if not stale:
                self._last_seq[msg.sender] = (inc, seq)
        self._running = {k: t for k, t in self._running.items() if not t.done()}
        batch = Batch(
            job_id=key[0], batch_id=key[1], model=d["model"],
            files=list(d["files"]),
            replicas={f: list(r) for f, r in d.get("replicas", {}).items()},
            versions={f: int(v) for f, v in d.get("versions", {}).items()},
            streams={
                f: list(v) for f, v in (d.get("streams") or {}).items()
            },
            inline_results=bool(d.get("inline")),
            traces=[
                e for e in (d.get("traces") or []) if isinstance(e, dict)
            ],
        )
        if key in self._running:
            return  # duplicate/re-sent delivery of a running batch
        if d.get("staged"):
            # pipeline assignment: start the prepare (store fetch +
            # host decode) NOW; dispatch happens when the running
            # batch's inference completes (promotion)
            if stale:
                return  # a reordered old stage; the resend tick re-stages
            if self._staged is not None:
                if self._staged[0] == key:
                    return  # duplicate staged delivery
                self._staged[3].cancel()
            prep = asyncio.create_task(
                self._prepare(batch),
                name=f"{self.node.me}-prep-{key[0]}-{key[1]}",
            )
            self._staged = (key, batch, msg.sender, prep)
            if not self._running:
                # UDP reorder: the stage outran its same-round primary.
                # Hold it staged (executing it now would later be
                # cancelled as a 'preemption' when the primary lands);
                # if the primary never arrives, self-promote after a
                # beat so the batch isn't stranded until the resend.
                self._spawn_bg(
                    self._promote_orphaned_stage(key),
                    f"orphan-stage promotion {key}",
                )
            return
        if self._running:
            # a different batch while busy = preemption (reference
            # worker.py:944-953): cancel the host-side tasks; the
            # coordinator already requeued the displaced batches
            # (primary AND stage). Model weights stay resident in HBM.
            # A STALE reordered request must not cancel newer work.
            if stale:
                return
            for t in self._running.values():
                t.cancel()
            self._running.clear()
            if self._staged is not None and self._staged[0] != key:
                self._staged[3].cancel()
                self._staged = None
        # idle (or just preempted): run it — even a stale-seq request
        # cancels nothing here, and completion dedup absorbs re-runs
        if self._staged is not None and self._staged[0] == key:
            # the primary for a batch we already staged (normal-order
            # promotion resend, or the reordered-primary case above):
            # reuse its in-flight prepare
            _, sbatch, _, prep = self._staged
            self._staged = None
            task = asyncio.create_task(
                self._execute(sbatch, coordinator=msg.sender, prep=prep),
                name=f"{self.node.me}-task-{key[0]}-{key[1]}",
            )
        else:
            task = asyncio.create_task(
                self._execute(batch, coordinator=msg.sender),
                name=f"{self.node.me}-task-{key[0]}-{key[1]}",
            )
        self._running[key] = task

    async def _promote_orphaned_stage(self, key: Tuple[int, int]) -> None:
        """Fallback for a stage whose primary was lost or reordered
        away: after a beat, if the stage is still parked and the worker
        is idle, run it rather than strand it until the coordinator's
        resend timeout."""
        await asyncio.sleep(2 * self.node.spec.timing.ping_interval)
        self._running = {k: t for k, t in self._running.items() if not t.done()}
        if (
            self._staged is not None
            and self._staged[0] == key
            and not self._running
        ):
            log.info("%s: promoting orphaned stage %s", self._me, key)
            self._promote_staged()

    def _spawn_bg(self, coro, what: str) -> asyncio.Task:
        """Fire-and-forget with a strong reference (the loop keeps only
        weak refs — an untracked task can be GC'd before it runs) and
        exception logging (otherwise failures vanish as 'exception was
        never retrieved')."""
        t = asyncio.create_task(coro, name=f"{self._me}-{what}")
        self._bg_tasks.add(t)

        def _done(task: asyncio.Task) -> None:
            self._bg_tasks.discard(task)
            if not task.cancelled() and task.exception() is not None:
                log.error(
                    "%s: background %s failed: %r",
                    self._me, what, task.exception(),
                )

        t.add_done_callback(_done)
        return t

    async def _h_stage_cancel(self, msg: Message, addr) -> None:
        """The coordinator revoked our staged batch (it went back to
        the queue when a second model's work arrived). If it already
        promoted to running, let it finish — completion dedup absorbs
        the duplicate. Carries the same (inc, seq) staleness guard as
        assignments so a reordered old cancel can't kill a NEWER
        re-stage of the same batch."""
        seq = int(msg.data.get("seq", 0))
        inc = int(msg.data.get("inc", 0))
        if seq:
            prev_inc, prev_seq = self._last_seq.get(msg.sender, (0, 0))
            if inc < prev_inc or (inc == prev_inc and seq <= prev_seq):
                return
            self._last_seq[msg.sender] = (inc, seq)
        key = (int(msg.data["job"]), int(msg.data["batch"]))
        if self._staged is not None and self._staged[0] == key:
            self._staged[3].cancel()
            self._staged = None

    def _promote_staged(self) -> None:
        """Start executing the staged batch (its prepare is already in
        flight). Called the moment the current batch's inference is
        dispatched (engine path) or finished (generic path): the
        coordinator performs the matching in_progress promotion when
        the current batch's ACK arrives."""
        if self._staged is None:
            return
        key, batch, coordinator, prep = self._staged
        self._staged = None
        task = asyncio.create_task(
            self._execute(batch, coordinator=coordinator, prep=prep),
            name=f"{self.node.me}-task-{key[0]}-{key[1]}",
        )
        self._running[key] = task

    async def _prepare(
        self, batch: Batch
    ) -> Tuple[List[str], Optional[Any], float, float, float, float]:
        """Stage 1 of the worker pipeline: materialize the batch's
        inputs locally and (for engine-served CNN models) decode them
        to the uint8 batch array. Runs eagerly for staged batches so
        it overlaps the previous batch's device time. Returns its own
        start AND end times so exec accounting spans the true first
        touch (for a staged batch, _execute begins long after prepare
        did) and the parked time between prepare finishing and the
        batch's promotion is attributable (`stage_wait` in the
        breakdown, VERDICT r4 item 4)."""
        t0 = time.monotonic()
        paths = await self._fetch_inputs(batch)
        t_fetch = time.monotonic() - t0
        imgs = None
        t_decode = 0.0
        # the engine path pre-decodes; skip it when the batch will run
        # on the GROUP engine (which decodes at its own mesh shapes) —
        # otherwise every group batch pays the host JPEG decode twice.
        # If the role flips between prepare and execute, the generic
        # engine fallback decodes internally, so skipping stays safe.
        if (
            self._backend_is_engine
            and batch.model not in self._extra_backends
            and not self._group_serves(batch.model)
        ):
            try:
                spec = get_model(batch.model)
            except KeyError:
                spec = None
            if spec is not None:
                t1 = time.monotonic()
                imgs = await asyncio.to_thread(
                    self._decode_cached, paths, spec.input_size
                )
                t_decode = time.monotonic() - t1
        return paths, imgs, t_fetch, t_decode, t0, time.monotonic()

    def _decode_cached(self, paths: List[str], size) -> Any:
        """load_images through the per-file decoded cache (thread
        context). Cache keys carry mtime+size so an overwritten local
        file can never serve a stale decode."""
        import numpy as np

        from ..models.preprocess import load_images

        if self.decode_cache_bytes <= 0:
            return load_images(paths, size)
        keys = []
        for p in paths:
            try:
                st = os.stat(p)
                keys.append((p, st.st_mtime_ns, st.st_size, tuple(size)))
            except OSError:
                keys.append(None)
        out: List[Optional[Any]] = [None] * len(paths)
        miss_idx = []
        with self._decode_cache_lock:
            for i, k in enumerate(keys):
                hit = self._decode_cache.get(k) if k is not None else None
                if hit is not None:
                    self._decode_cache.move_to_end(k)
                    self.decode_cache_hits += 1
                    out[i] = hit
                else:
                    self.decode_cache_misses += 1
                    miss_idx.append(i)
        if miss_idx:
            _M_CACHE_MISSES.inc(len(miss_idx))
        if len(paths) - len(miss_idx):
            _M_CACHE_HITS.inc(len(paths) - len(miss_idx))
        if miss_idx:
            decoded = load_images([paths[i] for i in miss_idx], size)
            with self._decode_cache_lock:
                for j, i in enumerate(miss_idx):
                    # copy the slice out of the batch array: caching the
                    # view would pin the WHOLE decoded batch base while
                    # the byte accounting counts only the slice
                    arr = np.ascontiguousarray(decoded[j])
                    out[i] = arr
                    k = keys[i]
                    if k is not None and k not in self._decode_cache:
                        self._decode_cache[k] = arr
                        self._decode_cache_used += arr.nbytes
        with self._decode_cache_lock:
            while (
                self._decode_cache_used > self.decode_cache_bytes
                and self._decode_cache
            ):
                _, old = self._decode_cache.popitem(last=False)
                self._decode_cache_used -= old.nbytes
        return np.stack(out)

    async def _execute(
        self,
        batch: Batch,
        coordinator: str,
        prep: Optional[asyncio.Task] = None,
    ) -> None:
        import dataclasses as _dc

        from ..observability import span

        fanout: Optional[_StreamFanout] = None
        ctx_token = None
        trace_ctxs: List[TraceContext] = []
        try:
            with span("worker.fetch_inputs"):
                if prep is None:
                    (paths, imgs, t_fetch, t_decode, t0,
                     t_prep_end) = await self._prepare(batch)
                else:
                    paths, imgs, t_fetch, t_decode, t0, t_prep_end = await prep
            _M_FETCH.observe(t_fetch)
            t1 = time.monotonic()
            if batch.traces:
                # per-request trace contexts, re-keyed from sdfs name
                # to the LOCAL input path so backend internals (the
                # disagg LM prefill/handoff spans) can route contexts
                # per request without a side table. ALL contexts ride
                # the contextvar (the fallback-exemplar paths must see
                # unsampled requests too); the ordinary span loops
                # below gate on .sampled themselves.
                by_file = {}
                for e in batch.traces:
                    c = TraceContext.from_wire(e)
                    if c is not None:
                        by_file[c.key] = c
                all_ctxs = [
                    _dc.replace(c, key=p)
                    for p, f in zip(paths, batch.files)
                    if (c := by_file.get(f)) is not None
                ]
                trace_ctxs = [c for c in all_ctxs if c.sampled]
                # the fetch span is wall-positioned at the PREPARE
                # window (a staged batch's prepare ran long before
                # this dispatch)
                prep_end_wall = time.time() - max(
                    0.0, time.monotonic() - t_prep_end
                )
                for c in trace_ctxs:
                    TRACER.start_span(
                        "fetch", ctx=c, node=self._me,
                        t0=prep_end_wall - t_fetch - t_decode,
                        labels={"job": batch.job_id,
                                "batch": batch.batch_id,
                                "shared": len(batch.files)},
                    ).end(prep_end_wall)
                # batch-scoped contexts for instrumentation that
                # cannot thread them through its signature (store
                # put/get, the LM group backends); task-local via
                # contextvars, inherited by to_thread and subtasks
                ctx_token = CURRENT_CTXS.set(tuple(all_ctxs))
            # staged batches park between prepare finishing and
            # promotion (waiting out the previous batch's inference) —
            # a real, named stage of exec, not "other"
            stage_wait = max(0.0, t1 - t_prep_end)
            group_fields: Dict[str, Any] = {}
            be = self._extra_backends.get(batch.model, self._backend)
            gb = self._group_backend_for(batch.model)
            # _group_serves: a sharded group engine serves exactly
            # ONE model (gb.model; None = any, the lazy/stub
            # forms); any other model's batch falls through to the
            # single-chip backend — running the wrong forward
            # would ack wrong predictions silently. LM models
            # route to their own per-model sharded group backend
            # (weight-resident or disaggregated decode).
            group_serving = gb is not None and self._group_serves(batch.model)
            # ingress token streaming: a batch carrying stream targets
            # for a token-aware backend exposes per-request streams on
            # the data plane and tells each client where to pull
            # BEFORE decode starts (tokens flow while the batch runs).
            # Gated on the callable that will ACTUALLY serve the batch:
            # announcing streams a group engine never feeds would hand
            # clients an empty stream + EOF instead of the documented
            # degraded mode (tokens arrive with the final result).
            token_aware = (
                self._group_token_aware(gb) if group_serving
                else self._backend_token_aware.get(batch.model)
            )
            if batch.streams and token_aware:
                fanout = _StreamFanout(self, batch, paths)
            stream_kw = {"on_token": fanout.on_token} if fanout else {}
            infer_wall0 = time.time()
            with span("worker.inference"):
                if group_serving:
                    # formed-group PRIMARY: serve on the group's
                    # sharded engine (jobs/groups.py). The ACK
                    # advertises membership + capacity so the
                    # coordinator's fair-share weights track what the
                    # group actually is. A member dying mid-batch
                    # raises GroupDegraded out of the backend, riding
                    # the ordinary TASK_FAIL -> requeue path below.
                    results, infer_time, cost = await gb(
                        batch.model, paths, **stream_kw
                    )
                    g = self.groups.group_of(self._me)
                    members = self.groups.members(g.name) if g else ()
                    group_fields = {
                        "group": g.name if g else None,
                        "group_size": len(members),
                        "group_capacity": getattr(
                            gb, "capacity", float(len(members) or 1)
                        ),
                    }
                    self._promote_staged()
                elif imgs is not None and self._backend_is_engine:
                    results, infer_time, cost = await self._engine_infer_prepared(
                        batch.model, paths, imgs
                    )
                elif self._backend_dispatch_aware.get(batch.model):
                    # dispatch-aware backend (LMBackend): the staged
                    # next batch promotes the moment this batch's
                    # prompts enter the continuous-batching driver, so
                    # its decode JOINS the grid while this one drains
                    # (VERDICT r4 item 2). The callback fires on the
                    # driver thread — hop back to the loop.
                    loop = asyncio.get_running_loop()
                    results, infer_time, cost = await be(
                        batch.model, paths,
                        on_dispatch=lambda: loop.call_soon_threadsafe(
                            self._promote_staged
                        ),
                        **stream_kw,
                    )
                    # also promote now: covers backends whose serial
                    # mode never fires the callback, and a NEW stage
                    # that landed mid-drain (engine path does the same)
                    self._promote_staged()
                else:
                    results, infer_time, cost = await be(
                        batch.model, paths, **stream_kw
                    )
                    # generic path: promote once inference finished
                    # (the engine path promoted at dispatch)
                    self._promote_staged()
            if fanout is not None:
                fanout.close()
            t_backend = (time.monotonic() - t1) + t_decode
            _M_INFER.observe(infer_time)
            infer_wall1 = time.time()
            for c in trace_ctxs:
                # the span covers the backend CALL wall (the request
                # sat in this stage that long); the device-only
                # portion rides as a label
                TRACER.start_span(
                    "infer", ctx=c, node=self._me, t0=infer_wall0,
                    labels={"job": batch.job_id,
                            "batch": batch.batch_id,
                            "model": batch.model,
                            "infer_s": round(infer_time, 6),
                            "shared": len(batch.files)},
                ).end(infer_wall1)
            # backends key results by the LOCAL path (the engine uses
            # the full path, others may use the basename), which
            # differs by how the input materialized (store-replica hit
            # -> name_versionN, data-plane download -> name.vN). Re-key
            # to the sdfs names so merged job output is consistent no
            # matter which worker classified which image.
            to_sdfs = {}
            for p, f in zip(paths, batch.files):
                to_sdfs[p] = f
                to_sdfs[os.path.basename(p)] = f
            results = {to_sdfs.get(k, k): v for k, v in results.items()}
            # inline-results (ingress) batches ride the ACK when they
            # fit a datagram, skipping the 3x-replicated store PUT per
            # batch — the per-request serving path cannot afford one
            # replicated object per formed batch, and nothing ever
            # get-output's an ingress job. Oversized results (or
            # ordinary jobs) take the store path unchanged.
            inline_payload: Optional[Dict[str, Any]] = None
            if batch.inline_results:
                blob = json.dumps(results)
                if len(blob) <= 40_000:
                    inline_payload = results
            t_put0 = time.monotonic()
            if inline_payload is None:
                out_name = f"output_{batch.job_id}_{batch.batch_id}_{self.node.me.port}.json"
                tmp = os.path.join(self.store.cfg.download_path(), out_name)
                os.makedirs(os.path.dirname(tmp), exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(results, f)
                try:
                    # timeout scales with the cluster's RPC envelope
                    # (capped at the old fixed 60 s): a worker wedged
                    # publishing output under churn holds its batch
                    # un-ACKed (and the job un-finishable) far past an
                    # aggressive-timing cluster's whole recovery window
                    await self.store.put(
                        tmp, out_name,
                        timeout=min(
                            60.0,
                            4 * self.node.spec.timing.leader_rpc_timeout,
                        ),
                    )
                except Exception as e:
                    # store unavailable (e.g. mid-failover): the ACK
                    # still carries the result timing; get-output will
                    # miss this shard, which the reference tolerates
                    # identically
                    log.warning("%s: PUT of %s failed: %s",
                                self._me, out_name, e)
            t_put = time.monotonic() - t_put0
            _M_PUT.observe(t_put)
            put_wall1 = time.time()
            for c in trace_ctxs:
                TRACER.start_span(
                    "put", ctx=c, node=self._me, t0=put_wall1 - t_put,
                    labels={"job": batch.job_id,
                            "batch": batch.batch_id,
                            "inline": int(inline_payload is not None)},
                ).end(put_wall1)
            _M_BATCHES.inc(model=batch.model)
            # the wall we REPORT is measured here, BEFORE the liar
            # seam's stall below: an injected liar keeps its metrics
            # clean and only the coordinator's own dispatch->ACK clock
            # (signal.HealthScorer cross-check) sees the truth
            exec_wall = time.monotonic() - t0
            liar_extra = self.liar_extra_s
            if liar_extra > 0:
                await asyncio.sleep(liar_extra)
            self.node.send_unique(
                coordinator if self.node.leader_unique is None else self.node.leader_unique,
                MsgType.WORKER_TASK_REQUEST_ACK,
                {
                    "job": batch.job_id,
                    "batch": batch.batch_id,
                    "model": batch.model,
                    "n_images": len(batch.files),
                    "exec_time": exec_wall,
                    "infer_time": infer_time,
                    # where the batch's wall time went (VERDICT r2
                    # item 9): replica fetch vs backend (backend −
                    # infer ≈ host JPEG decode) vs staged-parking vs
                    # output PUT; the coordinator aggregates these
                    # into breakdown_stats()
                    "fetch_time": t_fetch,
                    "backend_time": t_backend,
                    "stage_wait_time": stage_wait,
                    "put_time": t_put,
                    "cost": cost,
                    **({"results": inline_payload}
                       if inline_payload is not None else {}),
                    **group_fields,
                },
            )
            # a staged batch that arrived while we were draining (the
            # engine path promotes at dispatch, but the NEXT stage can
            # land mid-drain) starts now
            self._promote_staged()
        except asyncio.CancelledError:
            log.info("%s: batch %s preempted", self._me, batch.key)
            raise
        except Exception as e:
            log.exception("%s: batch %s failed", self._me, batch.key)
            _M_BATCH_FAILS.inc(model=batch.model)
            # tell the coordinator so it requeues the batch and frees
            # this worker — silence would wedge the job forever
            self.node.send_unique(
                coordinator if self.node.leader_unique is None else self.node.leader_unique,
                MsgType.WORKER_TASK_FAIL,
                {"job": batch.job_id, "batch": batch.batch_id, "error": str(e)},
            )
            # the staged batch is independent work: run it (the
            # coordinator's on_batch_failed does the same promotion)
            self._promote_staged()
        finally:
            if ctx_token is not None:
                CURRENT_CTXS.reset(ctx_token)
            if fanout is not None:
                # idempotent: normal completion already closed; this
                # covers failure/preemption — a stream always EOFs
                fanout.close()
            t = self._running.get(batch.key)
            if t is not None and t is asyncio.current_task():
                del self._running[batch.key]

    async def _fetch_inputs(self, batch: Batch) -> List[str]:
        """Materialize the batch's images locally: local store hit if
        this node replicates the file, else pull from a live replica
        over the data plane (reference scp-per-image,
        run_inference_cli worker.py:1361-1386)."""
        dl = self.store.cfg.download_path()
        os.makedirs(dl, exist_ok=True)
        paths: List[str] = []
        for f in batch.files:
            want = batch.versions.get(f, 0) or None
            if self.store.store.has(f, want):
                paths.append(self.store.store.get_path(f, want))
                continue
            # version-qualified cache name: a re-PUT of the same sdfs
            # name must never be served from a stale cached download
            dest = os.path.join(dl, f"{f.replace('/', '_')}.v{want or 'latest'}")
            if want is not None and os.path.exists(dest):
                paths.append(dest)
                continue
            fetched = False
            for uname in batch.replicas.get(f, []):
                node = self.node.spec.node_by_unique_name(uname)
                if node is None:
                    continue
                try:
                    data, _ = await self.store.data_plane.fetch_from_store(
                        data_addr(node), f, want
                    )
                    with open(dest, "wb") as fh:
                        fh.write(data)
                    paths.append(dest)
                    fetched = True
                    break
                except Exception:
                    continue
            if not fetched:
                raise RuntimeError(f"no live replica served {f}")
        return paths

    # ------------------------------------------------------------------
    # client-side completion handler
    # ------------------------------------------------------------------

    async def _h_job_success(self, msg: Message, addr) -> None:
        job_id = int(msg.data.get("job_id", -1))
        fut = self._job_done.setdefault(
            job_id, asyncio.get_running_loop().create_future()
        )
        if not fut.done():
            fut.set_result(dict(msg.data))

    # ------------------------------------------------------------------
    # model-weight distribution (store-backed; inference/weights.py)
    # ------------------------------------------------------------------

    async def publish_model(self, model: str) -> Dict[str, Any]:
        """Publish this node's current weights for `model` into the
        replicated store (loads/initializes the model first if needed)."""
        from ..inference.weights import publish_weights

        eng = self._ensure_engine()
        name = get_model(model).name
        if name not in eng.loaded_models:
            await asyncio.to_thread(eng.load_model, name)
        lm = eng._require(name)
        import jax

        return await publish_weights(
            self.store, name, jax.device_get(lm.variables)
        )

    async def load_model_weights(
        self, model: str, version: Optional[int] = None
    ) -> None:
        """Fetch published weights from the store and (re)load the
        serving engine with them."""
        from ..inference.weights import fetch_weights

        from ..inference.weights import weights_name

        eng = self._ensure_engine()
        name = get_model(model).name
        if version is None:
            # pin "latest" NOW: the served version must be recoverable
            # later even if newer versions get published in between
            listing = await self.store.ls_all(weights_name(name))
            vs = listing.get(weights_name(name))
            version = max(vs) if vs else None
        variables = await fetch_weights(self.store, name, version=version)
        # engine.load_model keeps the serving batch size across a
        # reload (a C3 set_batch_size survives a weight rollout)
        await asyncio.to_thread(eng.load_model, name, variables)
        # the GROUP engine must serve the same weights: group-served
        # and single-chip answers for one model may never differ by
        # formation state (jobs/groups.py group_engine_backend)
        setv = getattr(self._group_backend, "set_variables", None)
        if setv is not None:
            setv(name, variables)
        self._served_weight_version[name] = version

    JOBS_CKPT_NAME = "coordinator_jobs.ckpt"

    async def checkpoint_jobs(self) -> Dict[str, Any]:
        """Coordinator-only: snapshot the scheduler (queues, in-flight
        folded to queue fronts, job states, counters, measured costs)
        into the replicated store. Survives a FULL cluster restart —
        the hot-standby relay (reference worker.py:887-919) only
        survives single-leader failover."""
        if self._me != self.node.leader_unique:
            raise RuntimeError("checkpoint-jobs runs on the coordinator")
        snap = self.scheduler.snapshot()
        return await self.store.put_bytes(
            self.JOBS_CKPT_NAME, json.dumps(snap).encode()
        )

    async def restore_jobs(
        self, version: Optional[int] = None, force: bool = False
    ) -> Dict[str, Any]:
        """Coordinator-only: restore a checkpoint_jobs() snapshot and
        resume scheduling the recovered queues.

        Refuses while jobs are live unless `force=True`: restore()
        replaces scheduler state wholesale, so a job submitted after
        the snapshot would vanish and its client would hang."""
        if self._me != self.node.leader_unique:
            raise RuntimeError("restore-jobs runs on the coordinator")
        if self.scheduler.jobs and not force:
            raise RuntimeError(
                f"{len(self.scheduler.jobs)} job(s) in flight would be "
                "dropped by the restore; pass force to override"
            )
        if version is None:
            # pin the version now so the standby relay below restores
            # the exact same snapshot
            version = self.store.metadata.latest_version(self.JOBS_CKPT_NAME)
        snap = json.loads(
            await self.store.get_bytes(self.JOBS_CKPT_NAME, version=version)
        )
        self.scheduler.restore(snap)
        stats = {
            "jobs": len(self.scheduler.jobs),
            "queued_batches": sum(
                len(q) for q in self.scheduler.queues.values()
            ),
        }
        # bump the relay generation FIRST: every relay sent from here
        # on (job submits, batch acks) carries gen >= this restore's,
        # so the standby can tell post-restore relays from pre-restore
        # ones regardless of UDP arrival order
        self._relay_gen += 1
        # bring the hot-standby's shadow up to the restored state —
        # without this, a failover right after a restore would promote
        # an empty shadow and drop every restored job. Retried until
        # the standby ACKs: one lost datagram must not silently void
        # the failover guarantee.
        # tracked via _spawn_bg (same teardown/logging contract as the
        # shadow-restore task above)
        self._spawn_bg(
            self._relay_restore_to_standby(version, self._relay_gen),
            "restore-relay",
        )
        self._run_schedule()
        return stats

    async def _relay_restore_to_standby(self, version: int, gen: int) -> None:
        for _ in range(5):
            sb = self.store.standby_node()
            if sb is None or sb.unique_name == self._me:
                return
            try:
                reply = await self.node.request(
                    sb, MsgType.JOBS_RESTORE_RELAY,
                    {"version": version, "gen": gen},
                    timeout=10.0,
                )
                if reply.get("ok"):
                    return
            except (TimeoutError, asyncio.TimeoutError):
                continue  # request() already waited out its timeout
            except asyncio.CancelledError:
                raise
            except Exception:
                # not just timeouts: ANY failure (encode error, socket
                # down, ...) must keep the retry loop alive so the
                # final "never acked" warning below is always reached
                # instead of the task dying silently. Fast-failing
                # errors need real spacing or all 5 attempts burn in
                # microseconds.
                log.exception(
                    "%s: restore relay attempt failed", self._me
                )
                await asyncio.sleep(1.0)
                continue
            await asyncio.sleep(1.0)  # replied but not ok: space retries
        log.warning(
            "%s: standby never acked snapshot v%d — its shadow may be "
            "stale until the next checkpoint", self._me, version,
        )

    def engine_memory_stats(self) -> Dict[str, Dict[str, float]]:
        """Resident models + HBM footprint (empty if the engine never
        started — don't boot jax just to report nothing)."""
        return self._engine.memory_stats() if self._engine else {}

    def unload_model(self, model: str) -> bool:
        """Evict a model's weights from HBM on this node."""
        return bool(self._engine) and self._engine.unload_model(model)

    def _ensure_engine(self):
        if self._engine is None:
            from ..inference.engine import InferenceEngine

            self._engine = InferenceEngine()
        return self._engine

    # ------------------------------------------------------------------
    # default inference backend: the TPU engine
    # ------------------------------------------------------------------

    async def _ensure_model_loaded(self, model: str):
        eng = self._ensure_engine()
        if model not in eng.loaded_models:
            if eng.evicted_with_explicit_weights(model):
                # recover the SAME weights the node was serving before
                # the eviction (pinned version — "latest" may since
                # have moved past a deliberate rollback); any other
                # load failure (OOM etc.) propagates untouched
                pinned = self._served_weight_version.get(
                    get_model(model).name
                )
                log.warning(
                    "%s: %s evicted with explicit weights; refetching "
                    "v%s from the store", self._me, model, pinned,
                )
                await self.load_model_weights(model, version=pinned)
            else:
                await asyncio.to_thread(eng.load_model, model)
        return eng

    async def _engine_backend(
        self, model: str, paths: List[str]
    ) -> Tuple[Dict[str, Any], float, Optional[Dict[str, float]]]:
        eng = await self._ensure_model_loaded(model)
        res = await eng.infer_files_async(model, paths)
        return res.to_json_dict(), res.infer_time, eng.cost_constants(model)

    async def _engine_infer_prepared(
        self, model: str, paths: List[str], imgs
    ) -> Tuple[Dict[str, Any], float, Optional[Dict[str, float]]]:
        """Pipelined engine path: inputs are already decoded. Enqueues
        the device forward WITHOUT blocking (infer_arrays_nowait),
        promotes the staged batch so its dispatch overlaps this
        batch's drain, then drains in a thread. Through a remoted
        chip this turns the per-batch round-trip latency into
        pipeline depth."""
        from ..models.labels import decode_predictions

        eng = await self._ensure_model_loaded(model)
        t0 = time.monotonic()
        loop = asyncio.get_running_loop()

        def dispatch_and_drain():
            # dispatch AND drain off the event loop: device_put + jit
            # dispatch through a remoted chip block for tens of ms,
            # which on the loop would stall the whole control plane
            # (heartbeats, ACKs, scheduling) per batch
            handle = eng.infer_arrays_nowait(model, imgs)
            # batch N+1 dispatches while we drain batch N
            loop.call_soon_threadsafe(self._promote_staged)
            return handle()

        probs = await asyncio.to_thread(dispatch_and_drain)
        infer_time = time.monotonic() - t0
        top5 = decode_predictions(probs)
        results = {
            p: [
                {"wnid": w, "label": lbl, "score": s}
                for (w, lbl, s) in t
            ]
            for p, t in zip(paths, top5)
        }
        return results, infer_time, eng.cost_constants(model)
