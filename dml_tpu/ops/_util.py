"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import jax


def interpret_default() -> bool:
    """Compile via Mosaic on TPU; run the Pallas interpreter elsewhere
    (the CPU test mesh)."""
    return jax.default_backend() != "tpu"
