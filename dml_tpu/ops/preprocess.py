"""Fused image-normalize Pallas kernel: uint8 ingest -> model dtype.

The serving hot path feeds every forward pass a uint8 [N, H, W, 3]
batch (models/preprocess.py keeps host->HBM transfers uint8 on
purpose). This kernel does the cast + channel flip + mean/scale in a
single VMEM pass, per preprocessing mode ("caffe"/"tf"/"unit"), as
the Pallas counterpart of `normalize_on_device` — one HBM read, one
HBM write, no intermediate f32 image in HBM.

The image is viewed as [N*H, W*3] so the lane dimension is a
multiple of 3 channels; per-channel constants are applied via a
modulo-3 lane mask instead of a gather (TPU-friendly: iota + where).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import interpret_default as _interpret_default

from ..models.preprocess import _CAFFE_MEAN_BGR


def _normalize_kernel(x_ref, o_ref, *, mode, width3):
    # Mosaic has no direct uint8 -> f32 cast; hop through int32
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32)  # [rows, W*3]
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    c = lane % 3  # channel id per lane (RGB interleaved)
    if mode == "caffe":
        # RGB -> BGR flip = per-pixel lane swap of channels 0 and 2:
        # out[c] = in[2-c]; realized by shifting lanes +/-2 and
        # selecting by channel id (pltpu.roll is a cheap lane shift)
        x_left = pltpu.roll(x, width3 - 2, 1)   # lane i <- lane i+2
        x_right = pltpu.roll(x, 2, 1)           # lane i <- lane i-2
        x = jnp.where(c == 0, x_left, jnp.where(c == 2, x_right, x))
        mean = jnp.where(
            c == 0, _CAFFE_MEAN_BGR[0],
            jnp.where(c == 1, _CAFFE_MEAN_BGR[1], _CAFFE_MEAN_BGR[2]),
        )
        x = x - mean
    elif mode == "tf":
        x = x / 127.5 - 1.0
    elif mode == "unit":
        x = x / 255.0
    o_ref[:] = x.astype(o_ref.dtype)


def normalize(x: jax.Array, mode: str, dtype=jnp.bfloat16) -> jax.Array:
    """Product entry point for batch normalization-preprocessing: the
    Mosaic kernel on TPU, XLA-fused jnp elsewhere.

    Why the kernel: XLA fuses an inline jnp normalize into the
    stride-2 7x7 stem conv, where overlapping receptive fields
    recompute it per patch; the kernel materializes the normalized
    batch once. Measured on v5e (ResNet50 end-to-end forward,
    slope-timed, r3): b8 0.751 ms vs 1.123 ms jnp (1.50x — small
    batches are stem-dominated), b32 parity (2.17 vs 2.14 ms), train
    step b32 +2%. Never slower, decisively faster at serving batch
    sizes below 32, so every product path uses it (engine, Trainer
    via normalize_sharded, sharded inference, __graft_entry__). On
    CPU the Mosaic interpreter would lose; jnp fuses fine."""
    if jax.default_backend() == "tpu":
        return fused_normalize(x, mode, dtype)
    from ..models.preprocess import normalize_on_device

    return normalize_on_device(x, mode, dtype)


def normalize_sharded(
    x: jax.Array, mode: str, dtype=jnp.bfloat16, mesh=None
) -> jax.Array:
    """`normalize` for pjit/mesh paths (Trainer, sharded inference).

    A pallas_call is a custom op GSPMD cannot auto-partition: inlined
    into a pjit program with a sharded batch it would force a full
    rematerialization (gather to one device, run, re-shard). On TPU
    with a mesh this wraps the kernel in `shard_map` over the batch
    (dp) axis so each device normalizes its own [N/dp] shard locally;
    with no mesh it is exactly `normalize`; off-TPU it stays jnp
    (whose fusion is fine there, and Mosaic-interpret would lose).
    """
    if jax.default_backend() != "tpu":
        from ..models.preprocess import normalize_on_device

        return normalize_on_device(x, mode, dtype)
    if mesh is None or getattr(mesh, "empty", False):
        return fused_normalize(x, mode, dtype)
    from functools import partial

    try:  # jax >= 0.8 promotes shard_map out of experimental
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P("dp", *(None,) * (x.ndim - 1))
    body = partial(fused_normalize, mode=mode, dtype=dtype)
    # the pallas_call inside can't express varying-mesh-axes metadata
    # on its out_shape, which jax>=0.8's shard_map rejects under its
    # default check_vma=True; disable the check (the body is trivially
    # per-shard). Older jax spells the flag check_rep.
    try:
        wrapped = shard_map(
            body, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False,
        )
    except TypeError:  # pragma: no cover - older jax
        wrapped = shard_map(
            body, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_rep=False,
        )
    return wrapped(x)


def fused_normalize(
    x: jax.Array,
    mode: str,
    dtype=jnp.bfloat16,
    *,
    block_rows: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """uint8 [N, H, W, 3] -> normalized `dtype` [N, H, W, 3].

    Pallas counterpart of models.preprocess.normalize_on_device; same
    modes ("caffe", "tf", "unit", "raw").
    """
    if mode == "raw":
        return x.astype(dtype)
    if mode not in ("caffe", "tf", "unit"):
        raise ValueError(f"unknown preprocess mode {mode!r}")
    if x.ndim != 4 or x.shape[-1] != 3:
        raise ValueError(f"expected [N,H,W,3], got {x.shape}")
    interpret = _interpret_default() if interpret is None else interpret
    n, h, w, _ = x.shape
    rows = n * h
    width3 = w * 3
    x2 = x.reshape(rows, width3)
    br = min(block_rows, rows)
    pad = (-rows) % br
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_normalize_kernel, mode=mode, width3=width3),
        grid=((rows + pad) // br,),
        in_specs=[pl.BlockSpec((br, width3), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, width3), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(((rows + pad), width3), dtype),
        interpret=interpret,
    )(x2)
    return out[:rows].reshape(n, h, w, 3)
