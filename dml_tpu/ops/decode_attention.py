"""Decode-step attention over the KV cache as a Pallas TPU kernel.

One autoregressive step attends a [B, 1, H, D] query against the full
[B, KV, T, D] cache — pure HBM streaming, ~zero FLOPs per byte. The
XLA einsum path has two measured problems on v5e (bench
`lm.decode_kv_heads_4k_ctx_b1` / `lm.kv_cache_int8_4k_ctx_b8`, r3):

- int8 KV caches (`LMConfig.kv_quant`): XLA does NOT fuse the dequant
  into the attention contraction — it materializes the whole cache as
  f32 in HBM first (4 bytes written + re-read per 1-byte cache
  element), making the half-size cache 0.59x the bf16 one. This
  kernel dequantizes inline: int8 values and f32 scales stream into
  VMEM, the f32 cache never exists in HBM, so int8's bandwidth
  advantage is real (capacity AND speed).
- MQA (KV=1): the grouped einsum leaves a [T, 64]-shaped stream whose
  trailing dim under-fills the 128-wide lanes, and XLA's schedule read
  4x less cache yet ran 24% SLOWER than GQA-4. Here every (batch,
  kv-head) program streams its cache block through VMEM once,
  grouped-query rows [G, T] in one dot, so MQA's smaller cache
  actually buys time.

Structure: grid (B, KV, k-blocks), online-softmax accumulation across
k-blocks in VMEM scratch (the decode-shaped sibling of
flash_attention.py's forward kernel — G = H/KV query rows instead of
a q-block). Per-slot validity (continuous batching: every slot sits
at its own position) arrives as an additive [B, T] bias computed by
XLA — 0 for cache positions <= pos[b], -1e30 beyond — so the kernel
needs no scalar prefetch and one code path serves single-request and
batched decode.

Math is f32 end-to-end like the einsum oracle it replaces
(inference/generate.py `batched_decode_step`), so parity holds to
float-associativity noise. The reference has no attention anywhere
(SURVEY §0); this serves the net-new LM path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import interpret_default as _interpret_default

NEG_INF = -1e30
LANES = 128  # scratch rows kept [G, 128]: full native tiles


def _decode_kernel(q_ref, k_ref, ks_ref, v_ref, vs_ref, bias_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, quantized, n_kv):
    ik = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    bias = bias_ref[:]  # [1, bk], shared by every head
    # static per-head loop: one grid instance streams ALL kv heads'
    # blocks (a per-(b, head) grid at decode sizes is dominated by
    # instance overhead — measured 42us vs XLA's 35us before folding
    # the head loop in)
    for h in range(n_kv):
        # MXU dots take the cache's own dtype (int8 -> bf16 is EXACT
        # for |v| <= 127); the per-position scales fold into the [G,
        # bk] score/probability rows AFTER the dot — 16x fewer
        # multiplies than dequantizing the [bk, D] block, and no f32
        # cache temporary in VMEM
        k = k_ref[h]
        if quantized:
            k = k.astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q_ref[h].astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, bk] f32
        if quantized:
            s = s * ks_ref[h]  # [1, bk] f32 scale row, exact in f32
        s = s + bias

        m_prev = m_scr[h, :, :1]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[h, :, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        v = v_ref[h]
        if quantized:
            p = p * vs_ref[h]  # fold the v scales into the prob rows
            v = v.astype(jnp.bfloat16)
        acc_scr[h] = acc_scr[h] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[h] = jnp.broadcast_to(m_new, m_scr.shape[1:])
        l_scr[h] = jnp.broadcast_to(l_new, l_scr.shape[1:])

    @pl.when(ik == nk - 1)
    def _finish():
        for h in range(n_kv):
            l = jnp.maximum(l_scr[h, :, :1], 1e-30)
            o_ref[h] = (acc_scr[h] / l).astype(o_ref.dtype)


def decode_attention(
    q: jax.Array,  # [B, 1, H, D]
    k: jax.Array,  # [B, KV, T, D] cache (cfg dtype, or int8 with scales)
    v: jax.Array,  # [B, KV, T, D]
    pos: jax.Array,  # [B] int32 — slot b attends cache positions <= pos[b]
    *,
    k_scale: Optional[jax.Array] = None,  # [B, KV, 1, T] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_k: int = 2048,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """One decode step of cache attention; returns [B, 1, H, D] f32.

    The cache is head-major ([B, KV, T, D] — `init_cache`'s layout):
    each head's [T, D] plane is contiguous, so the blocked axes are
    the trailing two, which is the only arrangement Mosaic's block
    constraint admits without a materialized transpose. H = KV * G
    grouped-query with kv-major head order (head h = kv * G + g),
    matching `batched_decode_step`'s reshape. Pass `k_scale`/`v_scale`
    to read an int8 cache with inline dequant."""
    b, one, h, d = q.shape
    if one != 1:
        raise ValueError(f"decode q must be [B,1,H,D], got {q.shape}")
    kv, t = k.shape[1], k.shape[2]
    if h % kv:
        raise ValueError(f"H {h} not divisible by KV {kv}")
    g = h // kv
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    scale = d ** -0.5 if scale is None else scale
    interpret = _interpret_default() if interpret is None else interpret

    # one grid instance holds all KV heads' blocks: clamp bk so each
    # stream's VMEM block (cache dtype; bf16 temporaries for int8)
    # stays ~<=1 MB
    itemsize = max(jnp.dtype(k.dtype).itemsize, 2)
    bk_cap = max(128, (2**20) // (kv * d * itemsize) // 128 * 128)
    bk = min(block_k, bk_cap, t)
    pad = (-t) % bk
    bias = jnp.where(
        jnp.arange(t)[None, :] <= pos[:, None], 0.0, NEG_INF
    ).astype(jnp.float32)[:, None, :]  # [B, 1, T]
    if pad:
        p4 = ((0, 0), (0, 0), (0, pad), (0, 0))
        k = jnp.pad(k, p4)
        v = jnp.pad(v, p4)
        if quantized:
            pT = ((0, 0), (0, 0), (0, 0), (0, pad))
            k_scale = jnp.pad(k_scale, pT)
            v_scale = jnp.pad(v_scale, pT)
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, pad)),
                       constant_values=NEG_INF)
    nk = (t + pad) // bk

    qg = q[:, 0].reshape(b, kv, g, d)
    q_spec = pl.BlockSpec((None, kv, g, d), lambda b_, j: (b_, 0, 0, 0))
    kv_spec = pl.BlockSpec((None, kv, bk, d), lambda b_, j: (b_, 0, j, 0))
    sc_spec = pl.BlockSpec((None, kv, 1, bk), lambda b_, j: (b_, 0, 0, j))
    bias_spec = pl.BlockSpec((None, 1, bk), lambda b_, j: (b_, 0, j))

    if quantized:
        ins = (qg, k, k_scale, v, v_scale, bias)
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, bias_spec]
    else:
        # the scale streams don't exist: don't DMA dummy buffers
        ins = (qg, k, v, bias)
        in_specs = [q_spec, kv_spec, kv_spec, bias_spec]

    def kernel(*refs):
        if quantized:
            q_r, k_r, ks_r, v_r, vs_r, b_r, o_r = refs[:7]
            scr = refs[7:]
        else:
            q_r, k_r, v_r, b_r, o_r = refs[:5]
            ks_r = vs_r = None
            scr = refs[5:]
        _decode_kernel(q_r, k_r, ks_r, v_r, vs_r, b_r, o_r, *scr,
                       scale=scale, quantized=quantized, n_kv=kv)

    out = pl.pallas_call(
        kernel,
        grid=(b, nk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, g, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((kv, g, LANES), jnp.float32),  # running max
            pltpu.VMEM((kv, g, LANES), jnp.float32),  # running denom
            pltpu.VMEM((kv, g, d), jnp.float32),      # output accum
        ],
        interpret=interpret,
    )(*ins)
    return out.reshape(b, 1, h, d)
