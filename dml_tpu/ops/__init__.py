"""Pallas TPU kernels for the framework's hot ops.

The compute path is JAX/XLA; these kernels cover the spots where
hand-placement beats the compiler's defaults:

- `flash_attention`: blockwise attention (online softmax) — the
  transformer serving/training hot op and the per-device block of the
  sp ring (parallel/ring_attention.py).
- `fused_normalize`: uint8 image -> normalized bf16/f32 in one VMEM
  pass. The serving engine uses it on TPU via `normalize` (measured
  ~10% faster end-to-end than letting XLA fuse the jnp normalize into
  the stem conv, which recomputes it across overlapping 7x7 stride-2
  patches); `normalize` falls back to the jnp path off-TPU.

Every kernel has an `interpret` escape hatch so the same code runs on
the CPU test mesh (tests/) and compiled on TPU.
"""

from .flash_attention import flash_attention
from .preprocess import fused_normalize, normalize

__all__ = ["flash_attention", "fused_normalize", "normalize"]
