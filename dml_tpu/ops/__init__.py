"""Pallas TPU kernels for the framework's hot ops.

The compute path is JAX/XLA; these kernels cover the spots where
hand-placement beats the compiler's defaults:

- `flash_attention`: blockwise attention (online softmax) — the
  transformer serving/training hot op and the per-device block of the
  sp ring (parallel/ring_attention.py).
- `fused_normalize`: uint8 image -> normalized bf16/f32 in one VMEM
  pass — the serving ingest op in front of every model forward
  (models/preprocess.py).

Every kernel has an `interpret` escape hatch so the same code runs on
the CPU test mesh (tests/) and compiled on TPU.
"""

from .flash_attention import flash_attention
from .preprocess import fused_normalize

__all__ = ["flash_attention", "fused_normalize"]
