"""Pallas TPU kernels for the framework's hot ops.

The compute path is JAX/XLA; these kernels cover the spots where
hand-placement beats the compiler's defaults:

- `flash_attention`: blockwise attention (online softmax) — the
  transformer serving/training hot op and the per-device block of the
  sp ring (parallel/ring_attention.py).
- `fused_normalize`: uint8 image -> normalized bf16/f32 in one VMEM
  pass — a drop-in Pallas alternative to `normalize_on_device`
  (models/preprocess.py), which the serving engine uses today (XLA
  already fuses the elementwise normalize into the first conv; this
  kernel exists for pipelines that want the ingest op standalone).

Every kernel has an `interpret` escape hatch so the same code runs on
the CPU test mesh (tests/) and compiled on TPU.
"""

from .flash_attention import flash_attention
from .preprocess import fused_normalize

__all__ = ["flash_attention", "fused_normalize"]
