"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

Blockwise attention with the online-softmax recurrence: the [T, T]
score matrix never materializes; each (batch, head, q-block) streams
over k-blocks accumulating output, running max, and running
denominator in VMEM scratch. The grid's innermost dimension is the
k-block index — TPU grids execute sequentially, so scratch carries
the accumulation across k-steps and the output block is written once
on the last step.

Backward is two more kernels with the standard recomputation split:
`dq` accumulates over k-blocks, `dk`/`dv` accumulate over q-blocks,
both driven by the saved per-row logsumexp and the precomputed
`delta = rowsum(dO * O)`.

This is the single-device analog of parallel/ring_attention.py: the
ring rotates KV chunks across chips via ppermute, this kernel streams
KV blocks through VMEM within a chip. Layout convention matches the
rest of the framework: [batch, seq, heads, head_dim] ("BTHD").

Performance notes (v5e, B4 T4096 H8 D128 bf16 causal, slope-timed):
the MXU dots take bf16 inputs with f32 accumulation — casting to f32
before the dot forces the ~4x slower f32 matmul path. Block sizes are
the other lever: 128x128 blocks run at ~10 TF/s (grid overhead
dominates), the 1024x1024 defaults at ~84 TF/s — 5.4x faster than
XLA's naive attention (8.9 ms -> 1.65 ms), which is HBM-bound on the
materialized [B,H,T,T] score tensor. Blocks are min'd to the actual
sequence length, so small-T callers are unaffected by the defaults.

The reference has no attention anywhere (SURVEY §0 — its models are
CNNs over single images); this is part of the net-new long-context
path, written per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import interpret_default as _interpret_default

NEG_INF = -1e30
# lane width: scratch for the per-row running stats is kept
# (block_q, 128) so every read/write is a full native tile
LANES = 128


def _causal_mask(s, iq, ik, block_q, block_k):
    qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _kv_valid_mask(s, ik, block_k, t_kv):
    """Mask k positions past the true sequence length (pad columns)."""
    kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos < t_kv, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, t_kv, padded_kv):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        # MXU wants the dot inputs in their native (bf16) dtype with
        # f32 accumulation — casting to f32 FIRST forces the ~4x
        # slower f32 matmul path (measured 9 -> 60+ TF/s on v5e)
        s = jax.lax.dot_general(
            q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if padded_kv:
            s = _kv_valid_mask(s, ik, block_k, t_kv)
        m_prev = m_scr[:, :1]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[:],
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks entirely above the diagonal
        pl.when(ik * block_k <= iq * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[:] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:, :1] + jnp.log(l)  # [bq, 1]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale, causal, block_q, block_k, t_kv,
                   padded_kv, has_glse):
    # rest = (glse_ref?, dq_ref, dq_scr): the lse-cotangent input only
    # exists for flash_attention_lse — the plain path must not stream
    # an all-zeros buffer through the kernel on every training step
    if has_glse:
        glse_ref, dq_ref, dq_scr = rest
    else:
        glse_ref, (dq_ref, dq_scr) = None, rest
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body():
        lse = lse_ref[:]                    # [bq, 1]
        delta = delta_ref[:]                # [bq, 1]
        s = jax.lax.dot_general(            # bf16 in, f32 accum (MXU)
            q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if padded_kv:
            s = _kv_valid_mask(s, ik, block_k, t_kv)
        p = jnp.exp(s - lse)                   # [bq, bk]
        dp = jax.lax.dot_general(              # dO @ V^T: [bq, bk]
            do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dL/ds = p*(dp - delta) from the out path + p*g_lse from the
        # lse output (d lse_i/d s_ij = p_ij) — the lse term exists
        # only for flash_attention_lse (e.g. the ring merge)
        row = (dp - delta + glse_ref[:]) if has_glse else (dp - delta)
        ds = p * row * scale
        dq_scr[:] += jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[:],
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(ik * block_k <= iq * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, scale, causal, block_q, block_k, t_kv,
                    padded_kv, has_glse):
    # rest = (glse_ref?, dk_ref, dv_ref, dk_scr, dv_scr) — see
    # _bwd_dq_kernel for why glse is statically optional
    if has_glse:
        glse_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        glse_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = None, rest
    # note the transposed grid: (b, h, k-block, q-block)
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body():
        lse = lse_ref[:]    # [bq, 1]
        delta = delta_ref[:]  # [bq, 1]
        s = jax.lax.dot_general(            # bf16 in, f32 accum (MXU)
            q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if padded_kv:
            s = _kv_valid_mask(s, ik, block_k, t_kv)
        p = jnp.exp(s - lse)  # [bq, bk] f32
        pb = p.astype(do_ref.dtype)
        dv_scr[:] += jax.lax.dot_general(  # P^T @ dO: [bk, D]
            pb, do_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row = (dp - delta + glse_ref[:]) if has_glse else (dp - delta)
        ds = (p * row * scale).astype(q_ref.dtype)
        dk_scr[:] += jax.lax.dot_general(  # dS^T @ Q: [bk, D]
            ds, q_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(ik * block_k <= iq * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _pad_seq(x, block):
    """Pad the seq axis (axis 2 of [B,H,T,D] / [B,H,T]) to a block
    multiple."""
    t = x.shape[2]
    pad = (-t) % block
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[2] = (0, pad)
    return jnp.pad(x, widths)


def _bhtd(x):
    return jnp.transpose(x, (0, 2, 1, 3))  # BTHD <-> BHTD (involution)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return out


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    """q,k,v: [B,H,T,D]. Returns (out [B,H,T,D], lse [B,H,T]) f32 lse."""
    b, h, t, d = q.shape
    t_kv = k.shape[2]
    bq = min(block_q, t)
    bk = min(block_k, t_kv)
    qp = _pad_seq(q, bq)
    kp = _pad_seq(k, bk)
    vp = _pad_seq(v, bk)
    nq = qp.shape[2] // bq
    nk = kp.shape[2] // bk
    padded_kv = kp.shape[2] != t_kv

    q_spec = pl.BlockSpec((None, None, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((None, None, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    o_spec = pl.BlockSpec((None, None, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    # rows stored [B, H, T, 1]: trailing singleton lane dim keeps the
    # block's last-two-dims (bq, 1) legal for Mosaic (bs0 == as0)
    lse_spec = pl.BlockSpec((None, None, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        t_kv=t_kv, padded_kv=padded_kv,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[o_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct((*qp.shape[:3], 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, LANES), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :t], lse[:, :, :t, 0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _flash_bwd_impl(q, k, v, out, lse, g, g_lse, causal, scale, block_q,
                    block_k, interpret):
    """Shared backward: dq/dk/dv given out-cotangent `g` and optional
    lse-cotangent `g_lse` ([B,H,T] f32, or None for plain attention —
    the g_lse input stream is then omitted from the kernels
    entirely)."""
    has_glse = g_lse is not None
    b, h, t, d = q.shape
    t_kv = k.shape[2]
    bq = min(block_q, t)
    bk = min(block_k, t_kv)
    # delta_i = sum_d dO_i O_i — the rowwise correction term
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B,H,T]

    qp, gp = _pad_seq(q, bq), _pad_seq(g, bq)
    kp, vp = _pad_seq(k, bk), _pad_seq(v, bk)
    # rows as [B, H, T, 1] (see forward); pad lse with +big so pad
    # q-rows produce p = exp(s - big) = 0
    lsep = _pad_seq(lse[..., None], bq)
    if lsep.shape[2] != t:
        pad_rows = (
            jax.lax.broadcasted_iota(jnp.int32, lsep.shape, 2) >= t
        )
        lsep = jnp.where(pad_rows, jnp.float32(-NEG_INF), lsep)
    deltap = _pad_seq(delta[..., None], bq)
    glsep = (
        _pad_seq(g_lse.astype(jnp.float32)[..., None], bq)
        if has_glse else None
    )
    nq = qp.shape[2] // bq
    nk = kp.shape[2] // bk
    padded_kv = kp.shape[2] != t_kv

    q_spec = pl.BlockSpec((None, None, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((None, None, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    row_spec = pl.BlockSpec((None, None, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0))

    ins = [qp, kp, vp, gp, lsep, deltap] + ([glsep] if has_glse else [])
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec] + (
        [row_spec] if has_glse else []
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, t_kv=t_kv, padded_kv=padded_kv,
            has_glse=has_glse,
        ),
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(*ins)[:, :, :t]

    # transposed grid: q-block innermost so dk/dv accumulate in scratch
    q_spec_t = pl.BlockSpec((None, None, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kv_spec_t = pl.BlockSpec((None, None, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0))
    row_spec_t = pl.BlockSpec((None, None, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0))
    in_specs_t = [q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t] + ([row_spec_t] if has_glse else [])
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, t_kv=t_kv, padded_kv=padded_kv,
            has_glse=has_glse,
        ),
        grid=(b, h, nk, nq),
        in_specs=in_specs_t,
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct(kp.shape, k.dtype),
            jax.ShapeDtypeStruct(vp.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(*ins)
    return dq, dk[:, :, :t_kv], dv[:, :, :t_kv]


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(
        q, k, v, out, lse, g, None, causal, scale,
        block_q, block_k, interpret,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# lse-returning variant (the ring-attention building block)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _flash_bwd_impl(
        q, k, v, out, lse, g_out, g_lse, causal, scale, block_q,
        block_k, interpret,
    )


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """Flash attention that ALSO returns the per-row log-sum-exp.

    q, k, v: [B, T, H, D] -> (out [B, Tq, H, D], lse [B, H, Tq] f32).
    Differentiable in both outputs (the lse cotangent feeds `ds` as
    `p * g_lse`), which is what lets ring attention merge per-block
    flash results across devices and still train. Layout matches
    `flash_attention`; `lse` stays [B, H, T] (the merge consumes it
    head-major)."""
    if q.ndim != 4:
        raise ValueError(f"expected [B,T,H,D], got {q.shape}")
    if causal and q.shape[1] != k.shape[1]:
        raise ValueError("causal attention needs equal q/k lengths")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    interpret = _interpret_default() if interpret is None else interpret
    out, lse = _flash_lse(
        _bhtd(q), _bhtd(k), _bhtd(v), causal, scale, block_q, block_k,
        interpret,
    )
    return _bhtd(out), lse


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise (flash) attention. q, k, v: [B, T, H, D] (T of k/v may
    differ from q's); returns [B, Tq, H, D] in q's dtype.

    Differentiable (custom VJP, both passes are Pallas kernels).
    `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (the CPU test mesh).
    """
    if q.ndim != 4:
        raise ValueError(f"expected [B,T,H,D], got {q.shape}")
    if causal and q.shape[1] != k.shape[1]:
        raise ValueError("causal attention needs equal q/k lengths")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    interpret = _interpret_default() if interpret is None else interpret
    out = _flash(
        _bhtd(q), _bhtd(k), _bhtd(v), causal, scale, block_q, block_k,
        interpret,
    )
    return _bhtd(out)
