"""Generate PARITY.md's performance table from a BENCH_r*.json.

VERDICT r2 item 2: round 2's hand-maintained perf table claimed a
cluster-serving number (196 q/s) that the driver's own capture
contradicted (110.6 q/s). Hand-edited tables drift; this tool makes
the table a pure function of the bench artifact:

- every cell is computed from named keys of ONE bench json (the file
  and its short sha1 are recorded on the marker line);
- `--write` splices the table into PARITY.md between
  `<!-- BENCH-TABLE:BEGIN ... -->` / `<!-- BENCH-TABLE:END -->`;
- tests/test_parity_table.py regenerates from the recorded source and
  fails if the committed table was edited by hand or went stale.

Run: ``python -m dml_tpu.tools.parity_table [--bench FILE] [--write]``
(default --bench: the highest-numbered BENCH_r*.json in the repo
root, preview files included).

Reference baseline numbers quoted in the left column come from the
reference's own measurements (reference test.py:109-131; SURVEY §6).
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PARITY_PATH = os.path.join(REPO_ROOT, "PARITY.md")
BEGIN_RE = re.compile(
    r"<!-- BENCH-TABLE:BEGIN source=(?P<src>\S+) sha1=(?P<sha>[0-9a-f]+) -->"
)
END_MARK = "<!-- BENCH-TABLE:END -->"


def latest_bench_path() -> Optional[str]:
    """Highest-round BENCH_r*.json in the repo root. Previews count,
    but on a same-round tie the driver's capture wins (the preview is
    the builder's stale stand-in once BENCH_rNN.json exists); ties
    otherwise break by name for determinism."""
    best = None
    best_key = (-1, -1, "")
    for p in sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json"))):
        name = os.path.basename(p)
        m = re.search(r"BENCH_r(\d+)", name)
        if not m:
            continue
        key = (int(m.group(1)), 0 if "preview" in name else 1, name)
        if key > best_key:
            best, best_key = p, key
    return best


def _short_sha1(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha1(f.read()).hexdigest()[:12]


def _num(x, nd=0):
    """Format a number; anything non-numeric renders as n/a (schema
    drift must degrade the cell, not crash the generator)."""
    if not isinstance(x, (int, float)):
        return "n/a"
    return f"{x:,.{nd}f}"


def _mfu_txt(mfu, label="MFU", prefix=" (", suffix=")"):
    """'(54% MFU)'-style fragment, or empty when absent — the ONE
    formatting site for MFU cells."""
    if not isinstance(mfu, (int, float)):
        return ""
    return f"{prefix}{mfu * 100:.0f}% {label}{suffix}"


def _summary_rows(s: Dict[str, Any]) -> List[List[str]]:
    """Rows computable from the compact driver summary alone (the
    artifact of record when only the driver's stdout tail survived).
    Fewer rows than the full matrix — every cell still traces to the
    driver capture, which is the point."""
    rows: List[List[str]] = []

    def row(metric: str, ref: str, ours: str) -> None:
        rows.append([metric, ref, ours])

    qps = s.get("headline_qps")
    if isinstance(qps, (int, float)) and qps > 0:
        row(
            "ResNet50 steady inference",
            "250 ms/image (4 q/s/node)",
            f"≈{1000.0/qps:.3f} ms/image at batch 32 (≈{_num(qps)} "
            f"q/s/chip{_mfu_txt(s.get('headline_mfu'), prefix=', ', suffix='')})",
        )
    if isinstance(s.get("c4_qps"), (int, float)):
        row(
            "Dual-model C4 fair-share", "manual 10-VM runs",
            f"{s['c4_qps']} q/s serving with the probe-chosen "
            f"'{s.get('c4_mode', 'n/a')}' dispatch "
            f"({s.get('pipelining', 'n/a')}× vs the reference-shaped "
            "sync loop)",
        )
    if isinstance(s.get("cluster_qps"), (int, float)):
        depth = s.get("cluster_depth")
        b128 = s.get("cluster_qps_b128")
        b128_txt = (
            f"; b128 {b128} q/s" if isinstance(b128, (int, float)) else ""
        )
        if depth is not None:  # r6+ schema: probe-adaptive serving
            detail = (
                f"adaptive depth (committed {depth}) — forced "
                f"statics: depth-1 {s.get('cluster_qps_unpipelined', 'n/a')} "
                f"q/s / depth-2 "
                f"{s.get('cluster_qps_pipelined_static', 'n/a')} q/s; "
                f"adaptive vs best static "
                f"{s.get('cluster_pipelining', 'n/a')}×"
            )
        else:  # r3..r5 schema: static depth-2 pipelining keys
            detail = (
                f"serial depth-1 {s.get('cluster_qps_unpipelined', 'n/a')} "
                f"q/s; static depth-2 pipelining ratio "
                f"{s.get('cluster_pipelining', 'n/a')}× (cold cache)"
            )
        row(
            "Cluster serving end-to-end (4 nodes, SDFS-replicated "
            "JPEGs, batch 32)",
            "≈0.8 q/s/node (25-image task in ~31 s)",
            f"≈{s['cluster_qps']} q/s through the full stack with "
            f"{detail}{b128_txt}",
        )
    if isinstance(s.get("cluster_lm_tok_s"), (int, float)):
        steady = s.get("cluster_lm_steady_tok_s")
        steady_txt = (
            f"; steady state (≥{_num(s.get('cluster_lm_steady_s', 15))} s "
            f"refill, ramp excluded) {_num(steady)} tok/s"
            if isinstance(steady, (int, float)) else ""
        )
        row(
            "Distributed LM serving end-to-end (4 nodes, "
            "store-replicated prompts)",
            "— (reference has no sequence serving)",
            f"{_num(s['cluster_lm_tok_s'])} gen tok/s transient"
            f"{steady_txt}",
        )
    lm_tok = s.get("lm_tok_s")
    if isinstance(lm_tok, dict) and lm_tok:
        row(
            "LM decode by weight form (B=1)", "—",
            ", ".join(
                f"{k} {_num(v)} tok/s" for k, v in lm_tok.items()
                if isinstance(v, (int, float))
            ),
        )
    if isinstance(s.get("cb_gain"), (int, float)):
        row("Continuous-batching decode (8 vs 1 slots)", "—",
            f"{s['cb_gain']}× aggregate")
    if isinstance(s.get("train_img_s"), (int, float)):
        row(
            "ResNet50 train step (fwd+bwd+SGD, b32)",
            "— (reference does no training)",
            f"{_num(s['train_img_s'])} img/s"
            + _mfu_txt(s.get("train_mfu"), label="fwd+bwd MFU"),
        )
    if isinstance(s.get("train_lm_tok_s"), (int, float)):
        row(
            "LM train step (198M, T=2048)",
            "— (reference does no training)",
            f"{_num(s['train_lm_tok_s'])} tok/s",
        )
    if isinstance(qps, (int, float)) and qps > 0:
        row("`vs_baseline` (bench.py headline)", "1×",
            f"≈{_num(qps / 4.0)}×")
    return rows


def render_table(bench: Dict[str, Any], source: str, sha1: str) -> str:
    """The markdown block, markers included. Missing sections render
    as 'n/a (pending next bench run)' so a schema change degrades the
    table instead of faking numbers. A driver capture recovered as
    summary-only renders the summary-derived rows and says so."""
    if bench.get("_summary_only"):
        rows = _summary_rows(bench.get("summary") or {})
        lines = [
            f"<!-- BENCH-TABLE:BEGIN source={source} sha1={sha1} -->",
            "",
            f"*Generated by `python -m dml_tpu.tools.parity_table` from "
            f"`{source}` (sha1 {sha1}) — do not edit by hand; "
            "tests/test_parity_table.py enforces this.*",
            "",
            "*Source is the DRIVER capture's compact summary (the "
            "artifact of record); per-section detail beyond these rows "
            "lives in the same-round preview artifact.*",
            "",
            "| Metric | Reference (CPU, CS425 VMs) | dml_tpu (1× TPU v5e) |",
            "|---|---|---|",
        ]
        for r in rows:
            lines.append("| " + " | ".join(r) + " |")
        if not rows:
            lines.append("| (driver summary carried no renderable "
                         "rows) | — | — |")
        lines += ["", END_MARK]
        return "\n".join(lines)
    m = bench.get("matrix", bench)
    rows: List[List[str]] = []

    def row(metric: str, ref: str, ours: str) -> None:
        rows.append([metric, ref, ours])

    hl = m.get("headline_resnet50_b32") or {}
    qps = hl.get("qps")
    if isinstance(qps, (int, float)) and qps > 0:
        mfu_txt = _mfu_txt(hl.get("mfu"), prefix=", ", suffix="")
        row(
            "ResNet50 steady inference",
            "250 ms/image (4 q/s/node)",
            f"≈{1000.0/qps:.3f} ms/image at batch 32 "
            f"(≈{_num(qps)} q/s/chip{mfu_txt})",
        )
    sweep = m.get("resnet50_sweep") or []
    if sweep:
        sweep_qps = [p["qps"] for p in sweep if "qps" in p]
        row(
            f"ResNet50 batch sweep {sweep[0]['batch']}..{sweep[-1]['batch']}",
            "—",
            f"{min(sweep_qps)/1000:.1f}k–{max(sweep_qps)/1000:.1f}k q/s; "
            f"batch {m.get('resnet50_throughput_optimal_batch', '?')} is "
            "throughput-optimal",
        )
    def _model_pts(points):
        out = []
        for p in points:
            if not isinstance(p.get("qps"), (int, float)):
                continue
            out.append(
                f"b{p.get('batch', '?')} ≈{p['qps']/1000:.1f}k q/s"
                + _mfu_txt(p.get("mfu"))
            )
        return ", ".join(out)

    inc = m.get("inceptionv3") or []
    if inc:
        row("InceptionV3 steady inference",
            "325 ms/image (3.1 q/s/node)", _model_pts(inc) + " per chip")
    b4 = m.get("efficientnet_b4") or []
    if b4:
        row("EfficientNet-B4 (plug-in model)", "—",
            _model_pts(b4) + " per chip")
    c4 = m.get("dual_model_c4") or {}
    if c4:
        if "combined_qps_auto" in c4:  # r5 schema: auto-chosen mode
            ours = (
                f"{c4['combined_qps_auto']} q/s serving with the "
                f"probe-chosen '{c4.get('dispatch_mode_auto', 'n/a')}' "
                f"dispatch ({c4.get('pipelining_speedup', 'n/a')}× vs "
                f"the reference-shaped sync loop); forced modes: sync "
                f"{c4.get('combined_qps_sync', 'n/a')} / pipelined "
                f"{c4.get('combined_qps_pipelined', 'n/a')} q/s "
                f"({c4.get('pipelined_vs_sync_forced', 'n/a')}×) "
                "through the real fair-share scheduler (tunnel "
                "dispatch included)"
            )
        elif "combined_qps_pipelined" in c4:  # r3/r4 schema
            ours = (
                f"{c4['combined_qps_sync']} q/s sync → "
                f"{c4['combined_qps_pipelined']} q/s with pipelined "
                f"dispatch ({c4.get('pipelining_speedup', 'n/a')}×) "
                "through the real fair-share scheduler (tunnel "
                "dispatch included)"
            )
        else:  # r2 schema
            ours = (
                f"{c4.get('combined_qps_incl_dispatch', 'n/a')} q/s "
                "incl. per-batch tunnel dispatch (capability, not peak "
                "— see sweep)"
            )
        row("Dual-model C4 fair-share", "manual 10-VM runs", ours)
    cs = m.get("cluster_serving") or {}
    if cs:
        extra = ""
        if "breakdown" in cs:
            b = cs["breakdown"]
            extra = " (" + ", ".join(
                f"{k} {v}" for k, v in b.items()
            ) + ")"
        fi = m.get("cluster_serving_failure") or {}
        fi_txt = ""
        if fi:
            fi_txt = (
                f"; worker killed mid-job "
                f"({fi.get('model', 'same model')}): "
                f"{fi.get('completed', 'n/a')}/{fi.get('queries', 'n/a')} "
                f"completed, detect→requeue "
                f"{fi.get('detect_to_requeue_s', 'n/a')} s, wall "
                f"{fi.get('wall_s', 'n/a')} s"
            )
        pipe_txt = ""
        if "adaptive" in cs:  # r6 schema: probe-adaptive depth
            ad = cs.get("adaptive") or {}
            d1c = cs.get("qps_depth1_static",
                         cs.get("qps_unpipelined", "n/a"))
            pipe_txt = (
                f" — reference serial loop "
                f"{cs.get('qps_unpipelined', 'n/a')} q/s; cache-matched "
                f"forced statics: depth-1 {d1c} / depth-2 "
                f"{cs.get('qps_pipelined_static', 'n/a')} q/s "
                f"({cs.get('pipelining_speedup_static', 'n/a')}×); the "
                f"adaptive controller committed depth "
                f"{ad.get('depth', 'n/a')} and served "
                f"{cs.get('qps_end_to_end', 'n/a')} q/s "
                f"({cs.get('pipelining_speedup', 'n/a')}× vs the "
                "better static)"
            )
        elif "qps_unpipelined" in cs:  # r3..r5 schema: static depth 2
            pipe_txt = (
                f" — serial worker loop {cs['qps_unpipelined']} q/s → "
                f"depth-2 pipelined "
                f"{cs.get('qps_pipelined_cold_cache', 'n/a')} q/s "
                f"({cs.get('pipelining_speedup', 'n/a')}×) → + decode "
                f"cache {cs.get('qps_end_to_end', 'n/a')} q/s"
            )
        tun = m.get("tunnel") or {}
        tun_txt = (
            f"; link weather this run: {_num(tun.get('upload_mb_per_s'))} "
            f"MB/s up, {_num(tun.get('readback_128kb_ms'), 1)} ms readback"
            if tun else ""
        )
        row(
            f"Cluster serving end-to-end ({cs.get('nodes', '?')} nodes, "
            "SDFS-replicated JPEGs, batch 32)",
            "≈0.8 q/s/node (25-image task in ~31 s)",
            f"≈{cs.get('qps_end_to_end', 'n/a')} q/s through the full "
            f"stack{pipe_txt}{extra}{fi_txt}{tun_txt}",
        )
    pl = m.get("pallas_on_device") or {}
    if pl:
        row(
            f"Flash-attention kernel ({pl.get('shape', '?')})",
            "—",
            f"{pl.get('flash_fwd_ms', 'n/a')} ms fwd, "
            f"{pl.get('flash_vs_naive_speedup', 'n/a')}× naive XLA; "
            f"ring body {pl.get('ring_flash_speedup', 'n/a')}× its "
            "dense form"
            + ("" if pl.get("parity_pass", True) else
               " — PARITY CHECK FAILED, see bench json"),
        )
    lm = m.get("lm") or {}
    if lm:
        forms = lm.get("decode_weight_forms_b1") or {}
        if forms:
            row(
                "LM decode by weight form "
                f"({lm.get('params_millions', '?')}M params, B=1)",
                "—",
                ", ".join(
                    f"{k} {_num(forms[k].get('tok_per_s'))} tok/s"
                    for k in ("f32", "bf16", "int8")
                    if isinstance(forms.get(k), dict)
                ),
            )
        heads = lm.get("decode_kv_heads_4k_ctx_b1") or {}
        if heads:
            row(
                "LM decode at 4k context by KV heads (B=1, bf16)",
                "—",
                ", ".join(
                    f"{k.upper()} {_num(heads[k].get('tok_per_s'))} tok/s"
                    for k in ("mha", "gqa4", "mqa")
                    if isinstance(heads.get(k), dict)
                )
                + f"; GQA-4 = {heads.get('gqa4_vs_mha_speedup', 'n/a')}× MHA",
            )
        kq = lm.get("kv_cache_int8_4k_ctx_b8") or {}
        if kq:
            row(
                "int8 KV cache at 4k context (B=8, GQA-4)",
                "—",
                f"bf16 cache {_num(kq.get('bf16_cache_tok_per_s'))} → "
                f"int8 cache {_num(kq.get('int8_cache_tok_per_s'))} "
                f"tok/s ({kq.get('speedup', 'n/a')}×); "
                f"{kq.get('cache_mb_per_slot_bf16', 'n/a')} → "
                f"{kq.get('cache_mb_per_slot_int8', 'n/a')} MB/slot",
            )
        pf = lm.get("prefill_2k_prompt") or {}
        if pf:
            row(
                "LM prefill vs token-by-token scan (2k prompt)",
                "—",
                f"{pf.get('prefill_ms', 'n/a')} ms vs "
                f"{pf.get('scan_ms_est', 'n/a')} ms "
                f"({pf.get('speedup', 'n/a')}×)",
            )
        cb = lm.get("continuous_batching") or {}
        if cb:
            s1 = (cb.get("slots_1") or {}).get("aggregate_tok_per_s")
            s8 = (cb.get("slots_8") or {}).get("aggregate_tok_per_s")
            row(
                "Continuous-batching decode (device program)",
                "—",
                f"1 slot {_num(s1)} → 8 slots {_num(s8)} tok/s aggregate "
                f"({cb.get('batching_gain_8_vs_1', 'n/a')}×)",
            )
    clm = m.get("cluster_lm_serving") or {}
    if clm and "gen_tok_per_s_end_to_end" in clm:
        row(
            f"Distributed LM serving end-to-end ({clm.get('nodes', '?')} "
            f"nodes, store-replicated prompts)",
            "— (reference has no sequence serving)",
            f"{clm.get('prompts', 'n/a')} prompts × "
            f"{clm.get('new_tokens_per_prompt', 'n/a')} new tokens in "
            f"{clm.get('wall_s', 'n/a')} s = "
            f"{_num(clm['gen_tok_per_s_end_to_end'])} gen tok/s through "
            "the full stack",
        )
    tr = m.get("train") or {}
    cnn_tr = tr.get("resnet50_b32") or {}
    if cnn_tr:
        row(
            "ResNet50 train step (fwd+bwd+SGD, b32)",
            "— (reference does no training)",
            f"{_num(cnn_tr.get('img_per_s'))} img/s"
            + _mfu_txt(cnn_tr.get("mfu_fwd_bwd"), label="fwd+bwd MFU")
            + f", {cnn_tr.get('step_ms', 'n/a')} ms/step",
        )
    lm_tr = tr.get("lm_198m_t2048") or {}
    if lm_tr:
        row(
            "LM train step (198M, T=2048)",
            "— (reference does no training)",
            f"{_num(lm_tr.get('tok_per_s'))} tok/s"
            + _mfu_txt(lm_tr.get("mfu_fwd_bwd"), label="fwd+bwd MFU")
            + f", {lm_tr.get('step_ms', 'n/a')} ms/step",
        )
    if isinstance(qps, (int, float)) and qps > 0:
        row("`vs_baseline` (bench.py headline)", "1×",
            f"≈{_num(qps / 4.0)}×")

    lines = [
        f"<!-- BENCH-TABLE:BEGIN source={source} sha1={sha1} -->",
        "",
        f"*Generated by `python -m dml_tpu.tools.parity_table` from "
        f"`{source}` (sha1 {sha1}) — do not edit by hand; "
        "tests/test_parity_table.py enforces this.*",
        "",
        "| Metric | Reference (CPU, CS425 VMs) | dml_tpu (1× TPU v5e) |",
        "|---|---|---|",
    ]
    for r in rows:
        lines.append("| " + " | ".join(r) + " |")
    if not rows:
        lines.append(
            "| (source file is a truncated driver wrapper — "
            "regenerate from a raw bench.py output) | — | — |"
        )
    lines += ["", END_MARK]
    return "\n".join(lines)


def load_bench(bench_path: str) -> Dict[str, Any]:
    """A bench artifact in any of its shipped forms:

    - the raw bench.py stdout saved as JSON (preview files) — the
      giant artifact line, parsed whole;
    - the driver's wrapper ({"cmd", "rc", "tail", ...}) whose 2,000-
      char `tail` usually truncates the artifact line. Recovery, in
      preference order: (1) the artifact line survived whole; (2) the
      bench's final STANDALONE compact summary line
      (``bench_summary_v1``, emitted since round 6 precisely to
      survive this tail); (3) the trailing ``summary`` object salvaged
      from the truncated artifact line (it is the artifact's LAST key
      by design). Salvaged forms carry ``_summary_only=True`` — the
      table renders from summary keys and says so.

    Only when none of that works does this degrade to
    ``{"_unparseable_wrapper": True}`` (deterministic empty table with
    a note) rather than aborting."""
    with open(bench_path) as f:
        data = json.load(f)
    if "tail" not in data or "metric" in data:
        return data
    tail = data["tail"]
    try:
        # raw_decode, not loads: a round-6+ tail holds the artifact
        # line FOLLOWED by the compact summary line — trailing data
        # must not disqualify an intact full artifact
        doc, _ = json.JSONDecoder().raw_decode(tail[tail.index("{"):])
        if isinstance(doc, dict) and (
            "matrix" in doc or "metric" in doc
        ):
            return doc
    except ValueError:
        pass  # no "{" / not JSON: fall through to the compact line
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if '"bench_summary_v1"' not in line:
            continue
        try:
            doc = json.loads(line[line.index("{"):])
        except Exception:
            continue
        doc["_summary_only"] = True
        return doc
    pos = tail.rfind('"summary"')
    if pos >= 0:
        try:
            start = tail.index("{", pos)
            summ, _ = json.JSONDecoder().raw_decode(tail[start:])
            if isinstance(summ, dict):
                return {"summary": summ, "_summary_only": True}
        except ValueError:
            pass  # truncated mid-summary: genuinely unparseable
    return {"_unparseable_wrapper": True}


def sanity_check(bench: Dict[str, Any]) -> List[str]:
    """Plausibility screen for a bench artifact — catches degenerate
    slope measurements (an r3 run recorded flash_fwd_ms = 0.0 and an
    8.8e6x 'speedup' when tunnel jitter swallowed a short chain)
    before they're committed into the published table. Returns a list
    of violations; empty = plausible. Ranges are generous physical
    bounds for one v5e-class chip, not expectations."""
    m = bench.get("matrix", bench)
    bad: List[str] = []

    def rng(path, val, lo, hi):
        if val is None:
            return
        if not isinstance(val, (int, float)) or not (lo <= val <= hi):
            bad.append(f"{path} = {val!r} outside [{lo}, {hi}]")

    if bench.get("_summary_only"):
        # driver-capture compact form: screen the summary-level numbers
        s = bench.get("summary") or {}
        rng("summary.headline_qps", s.get("headline_qps"), 1e3, 1e5)
        rng("summary.headline_mfu", s.get("headline_mfu"), 0.05, 1.0)
        rng("summary.cluster_qps", s.get("cluster_qps"), 1, 1e4)
        rng("summary.cluster_pipelining",
            s.get("cluster_pipelining"), 0.2, 20)
        rng("summary.cluster_lm_tok_s", s.get("cluster_lm_tok_s"), 0.5, 1e5)
        rng("summary.cluster_lm_steady_tok_s",
            s.get("cluster_lm_steady_tok_s"), 0.5, 1e5)
        rng("summary.train_img_s", s.get("train_img_s"), 10, 1e5)
        return bad

    hl = m.get("headline_resnet50_b32") or {}
    rng("headline.qps", hl.get("qps"), 1e3, 1e5)
    rng("headline.mfu", hl.get("mfu"), 0.05, 1.0)
    for pt in m.get("resnet50_sweep") or []:
        rng(f"sweep.b{pt.get('batch')}.qps", pt.get("qps"), 1e3, 1e5)
        rng(f"sweep.b{pt.get('batch')}.mfu", pt.get("mfu"), 0.01, 1.0)
    for section, lo, hi in (
        ("inceptionv3", 100, 5e4), ("efficientnet_b4", 50, 2e4)
    ):
        for pt in m.get(section) or []:
            rng(f"{section}.b{pt.get('batch')}.qps", pt.get("qps"), lo, hi)
            rng(f"{section}.b{pt.get('batch')}.mfu", pt.get("mfu"), 0.01, 1.0)
    pl = m.get("pallas_on_device") or {}
    rng("pallas.flash_fwd_ms", pl.get("flash_fwd_ms"), 0.2, 50)
    rng("pallas.flash_vs_naive_speedup",
        pl.get("flash_vs_naive_speedup"), 1, 100)
    rng("pallas.ring_flash_speedup", pl.get("ring_flash_speedup"), 1, 100)
    lm = m.get("lm") or {}
    for k, form in (lm.get("decode_weight_forms_b1") or {}).items():
        if isinstance(form, dict):
            rng(f"lm.forms.{k}.tok_per_s", form.get("tok_per_s"), 50, 5e4)
    for k, h in (lm.get("decode_kv_heads_4k_ctx_b1") or {}).items():
        if isinstance(h, dict):
            rng(f"lm.heads.{k}.tok_per_s", h.get("tok_per_s"), 50, 5e4)
    pf = lm.get("prefill_2k_prompt") or {}
    rng("lm.prefill_ms", pf.get("prefill_ms"), 1, 500)
    rng("lm.prefill_speedup", pf.get("speedup"), 2, 1000)
    cb = lm.get("continuous_batching") or {}
    rng("lm.cb.gain", cb.get("batching_gain_8_vs_1"), 0.5, 16)
    kq = lm.get("kv_cache_int8_4k_ctx_b8") or {}
    rng("lm.kv_int8.bf16_tok_per_s",
        kq.get("bf16_cache_tok_per_s"), 50, 1e5)
    rng("lm.kv_int8.int8_tok_per_s",
        kq.get("int8_cache_tok_per_s"), 50, 1e5)
    rng("lm.kv_int8.speedup", kq.get("speedup"), 0.05, 20)
    cs = m.get("cluster_serving") or {}
    rng("cluster.qps", cs.get("qps_end_to_end"), 1, 1e4)
    rng("cluster.qps_unpipelined", cs.get("qps_unpipelined"), 1, 1e4)
    rng("cluster.qps_depth1_static", cs.get("qps_depth1_static"), 1, 1e4)
    rng("cluster.qps_pipelined_static",
        cs.get("qps_pipelined_static"), 1, 1e4)
    rng("cluster.decode_cache_speedup",
        cs.get("decode_cache_speedup"), 0.2, 50)
    rng("cluster.pipelining_speedup", cs.get("pipelining_speedup"), 0.2, 20)
    rng("cluster.pipelining_speedup_static",
        cs.get("pipelining_speedup_static"), 0.2, 20)
    clm = m.get("cluster_lm_serving") or {}
    rng("cluster_lm.gen_tok_per_s",
        clm.get("gen_tok_per_s_end_to_end"), 0.5, 1e5)
    rng("cluster_lm.steady_tok_per_s",
        (clm.get("steady_state") or {}).get("gen_tok_per_s_steady"),
        0.5, 1e5)
    tr = m.get("train") or {}
    cnn_tr = tr.get("resnet50_b32") or {}
    rng("train.cnn.img_per_s", cnn_tr.get("img_per_s"), 10, 1e5)
    rng("train.cnn.step_ms", cnn_tr.get("step_ms"), 0.5, 1e4)
    rng("train.cnn.mfu", cnn_tr.get("mfu_fwd_bwd"), 0.01, 1.0)
    lm_tr = tr.get("lm_198m_t2048") or {}
    rng("train.lm.tok_per_s", lm_tr.get("tok_per_s"), 100, 1e7)
    rng("train.lm.step_ms", lm_tr.get("step_ms"), 0.5, 1e4)
    rng("train.lm.mfu", lm_tr.get("mfu_fwd_bwd"), 0.01, 1.0)
    tun = m.get("tunnel") or {}
    rng("tunnel.upload_mb_per_s", tun.get("upload_mb_per_s"), 0.1, 1e5)
    rng("tunnel.readback_ms", tun.get("readback_128kb_ms"), 0.01, 1e4)
    # a numerically broken kernel must not publish its speedup rows:
    # parity_pass=False is a hard refusal, not a table footnote
    if pl and pl.get("parity_pass", True) is False:
        bad.append(
            "pallas_on_device.parity_pass = False (kernel output "
            "diverged from the XLA oracle; timings are meaningless)"
        )
    return bad


def generate(bench_path: str) -> str:
    return render_table(
        load_bench(bench_path),
        os.path.basename(bench_path),
        _short_sha1(bench_path),
    )


def splice(parity_text: str, table: str) -> str:
    begin = BEGIN_RE.search(parity_text)
    end = parity_text.find(END_MARK)
    if not begin or end < 0:
        raise ValueError(
            "PARITY.md has no BENCH-TABLE markers; add "
            "'<!-- BENCH-TABLE:BEGIN source=x sha1=0 -->' and "
            f"'{END_MARK}' around the perf table once"
        )
    return (
        parity_text[: begin.start()]
        + table
        + parity_text[end + len(END_MARK):]
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bench", default=None, help="bench json path")
    ap.add_argument(
        "--write", action="store_true",
        help="splice the table into PARITY.md (default: print)",
    )
    args = ap.parse_args()
    bench_path = args.bench or latest_bench_path()
    if bench_path is None:
        raise SystemExit("no BENCH_r*.json found")
    # the plausibility screen gates generation, not just CI: a
    # degenerate slope artifact must be refused here, before an
    # implausible table can land in PARITY.md at all
    violations = sanity_check(load_bench(bench_path))
    if violations:
        raise SystemExit(
            f"{bench_path} fails the plausibility screen "
            f"(degenerate measurement?): {violations} — re-run the "
            "bench; see sanity_check()"
        )
    table = generate(bench_path)
    if args.write:
        with open(PARITY_PATH) as f:
            text = f.read()
        with open(PARITY_PATH, "w") as f:
            f.write(splice(text, table))
        print(f"PARITY.md table regenerated from {bench_path}")
    else:
        print(table)


if __name__ == "__main__":
    main()
