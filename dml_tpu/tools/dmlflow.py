"""dmlflow — flow-aware passes for dmllint: async-race windows and
wire-payload schema drift.

dmllint (PR 9) is lexical: it sees a naked ``create_task`` or a
pass-only ``except`` but cannot see ORDER. Every review pass since
PR 3 has hand-found the same two ordered bug classes the lexical rules
miss: check-then-act races on shared coordinator/router state that
span an ``await`` (the ACK-freshness, dedup-map and promoted-leader
adoption bugs), and wire-payload drift where a handler reads a field a
sender stopped (or never started) shipping. This module catches both
mechanically. It is pure AST + static introspection — no jax import,
both passes together cost about a second over the whole tree — and is
driven by ``dmllint.run_lint`` (same
Finding/baseline/exit-code machinery, same tier-1 zero-unbaselined
gate).

race-yield-hazard
-----------------

Per ``async def``, a statement-ordered model of reads/writes to
``self.*`` attributes and module-global mutable containers in
``dml_tpu/``. Two hazard shapes:

1. *check-then-act across a yield point*: a branch test reads
   ``self.x``, an ``await`` yields the event loop, and the code then
   mutates ``self.x`` without looking again — every other task gets a
   window between the check and the act. Recognized await-safe idioms
   (NOT flagged):

   - *re-check-after-await*: the same attribute appears in another
     branch test after the last await and before the mutation;
   - *lock-held window*: test and mutation sit inside the same
     ``async with self.<lock-ish>`` block (attribute names matching
     lock/mutex/sem/cond) — contenders serialize on the lock. Note the
     acquire itself is a yield point: testing BEFORE the ``async
     with`` and mutating inside it is still flagged (re-check inside
     the lock);
   - *snapshot-into-local*: copying ``self.x`` into a local before the
     await and testing/iterating the local — invisible to the rule by
     construction, because locals are never tracked.

2. *unrestored window marker*: an acquire-like mutation
   (``.add/.append/[k] =/= True/+= 1``) followed by an ``await`` and a
   release-like mutation (``.discard/.pop/del/.remove/= False/-= 1``)
   of the same attribute, where some await between the two is NOT
   inside the body of a ``try`` whose ``finally`` performs the
   release: a cancelled await skips the release and the marker leaks
   forever (the PR-3 wedge class, but for state instead of tasks).

drift-wire-payloads
-------------------

Infers each ``MsgType``'s payload schema from the whole package and
cross-checks it three ways:

- *send sites*: any call carrying a literal ``MsgType.X`` plus a
  resolvable payload dict (inline literal, or a local built up with
  ``d = {...}`` / ``d["k"] = v`` / ``d.update({...})``) — conditional
  assignments make a key *conditionally* sent; ``**``-spreads and
  computed keys make the site *opaque* (inference stops claiming
  completeness for that type). ``request/leader_request/leader_retry``
  sites implicitly ship ``rid``; ``rid`` is the universal correlation
  key and is excluded from all checks.
- *reads*: in the type's registered ``_h_*`` handler (via wire.py's
  HANDLER_OWNERS + the actual registrations), ``msg.data["k"]`` is a
  REQUIRED read, ``msg.data.get("k")`` / ``"k" in msg.data`` is
  OPTIONAL; ``d = msg.data`` aliases are followed, and one-call-deep
  delegation into same-class methods / same-module functions is
  resolved. For rid-fallback reply types, reads are collected at the
  *await site* of the owning request (``reply = await
  self.request(..., MsgType.Q, ...)``) and attributed through the
  payload map's ``<- Q`` reply annotations. Unresolvable flows mark
  the reader *open* (dead-byte claims stop for that type).
- *the payload map*: wire.py's module docstring carries a
  machine-readable "Payload map (lint-enforced)" section (one line per
  member: bare key = required, ``key?`` = optional, ``-`` = empty,
  ``*`` = open/unresolvable payload, ``<- REQUEST`` = reply-of
  annotation). Both directions are enforced: a key in the map nothing
  sends or reads, and a key on the wire the map doesn't declare, are
  findings — as are a missing member line, a ghost line, a wrong
  required/optional marking, and a ``*`` on a fully-resolved type.

Findings:

- ``required-never-sent`` — a handler (or await site) indexes a key NO
  sender of that type ever ships: a latent KeyError on the wire.
- ``required-not-always`` — a sender ships a required key only
  conditionally, or one sender of the type ships it and another never
  does (the conditional-send vs required-read disagreement).
- ``sent-never-read`` — a key every reader ignores: dead wire bytes.
- the map-sync findings described above.

Send sites inside the chaos byzantine fuzzer (``fuzz_datagrams``) are
deliberately adversarial and excluded via ``OFF_WIRE``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .dmllint import (
    Finding,
    R_PAYLOAD,
    R_RACE,
    extract_handler_owners,
    extract_msgtype_members,
    extract_registrations,
)

# ----------------------------------------------------------------------
# race-yield-hazard
# ----------------------------------------------------------------------

_LOCKISH = re.compile(r"lock|mutex|sem|cond", re.I)

#: container-mutating method names, split by window-marker polarity
ACQUIRE_METHODS = {
    "add", "append", "appendleft", "insert", "extend", "update",
    "setdefault",
}
RELEASE_METHODS = {"pop", "popleft", "remove", "discard", "clear"}
_MUTATORS = ACQUIRE_METHODS | RELEASE_METHODS

#: module-level constructors whose result is shared mutable state
_MUTABLE_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "Counter",
    "OrderedDict",
}


def module_mutable_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to a mutable container literal/ctor."""
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = node.value
            name = node.targets[0].id
            if isinstance(v, (ast.Dict, ast.List, ast.Set)):
                out.add(name)
            elif isinstance(v, ast.Call):
                f = v.func
                fname = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
                if fname in _MUTABLE_CTORS:
                    out.add(name)
    return out


class _RaceScan:
    """Statement-ordered event stream for ONE async function.

    Events: ``(kind, attr, line, lock_stack, try_stack, mkind)`` with
    kind in {test, read, mut, await}; attr is ``self.<name>`` or a
    module-global name; lock_stack is the tuple of lock-region ids
    held; try_stack is a tuple of (try_id, section) frames."""

    def __init__(self, mutable_globals: Set[str]) -> None:
        self.g = mutable_globals
        self.ev: List[Tuple[str, Optional[str], int, tuple, tuple, Optional[str]]] = []
        self.lock: tuple = ()
        self.tries: tuple = ()
        self._region = 0
        self._tryid = 0
        self._globaldecl: Set[str] = set()

    def emit(self, kind: str, attr: Optional[str], line: int,
             mkind: Optional[str] = None) -> None:
        self.ev.append((kind, attr, line, self.lock, self.tries, mkind))

    # -- base-attribute resolution -------------------------------------
    def _base(self, node: ast.AST) -> Optional[str]:
        while True:
            if isinstance(node, ast.Attribute):
                v = node.value
                if isinstance(v, ast.Name):
                    return f"self.{node.attr}" if v.id == "self" else None
                node = v
            elif isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Call):
                node = node.func
            elif isinstance(node, ast.Name):
                return node.id if node.id in self.g else None
            else:
                return None

    def _lockish(self, e: ast.AST) -> bool:
        n: ast.AST = e
        if isinstance(n, ast.Call):
            n = n.func
        if isinstance(n, ast.Subscript):
            n = n.value
        if isinstance(n, ast.Attribute):
            return bool(_LOCKISH.search(n.attr))
        if isinstance(n, ast.Name):
            return bool(_LOCKISH.search(n.id))
        return False

    def _mut_call(self, node: ast.Call) -> Optional[Tuple[str, Optional[str]]]:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            base = self._base(f.value)
            if base is not None:
                kind = "acq" if f.attr in ACQUIRE_METHODS else "rel"
                return base, kind
        return None

    # -- expressions ---------------------------------------------------
    def expr(self, node: Optional[ast.AST], test: bool = False) -> None:
        if node is None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # separate execution context
        if isinstance(node, ast.Await):
            self.expr(node.value, test)
            self.emit("await", None, node.lineno)
            return
        if isinstance(node, ast.IfExp):
            self.expr(node.test, test=True)
            self.expr(node.body, test)
            self.expr(node.orelse, test)
            return
        if isinstance(node, ast.Call):
            mt = self._mut_call(node)
            for a in node.args:
                self.expr(a, test)
            for kw in node.keywords:
                self.expr(kw.value, test)
            if mt is not None:
                self.emit("mut", mt[0], node.lineno, mt[1])
            else:
                self.expr(node.func, test)
            return
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            base = self._base(node)
            if base is not None:
                self.emit("test" if test else "read", base, node.lineno)
            if isinstance(node, ast.Subscript):
                self.expr(node.slice, test)
                if base is None:
                    self.expr(node.value, test)
            elif base is None:
                self.expr(node.value, test)
            return
        if isinstance(node, ast.Name):
            if node.id in self.g and isinstance(node.ctx, ast.Load):
                self.emit("test" if test else "read", node.id, node.lineno)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child, test)

    # -- assignment targets --------------------------------------------
    def target(self, t: ast.AST, mkind: Optional[str]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.target(e, None)
        elif isinstance(t, ast.Starred):
            self.target(t.value, None)
        elif isinstance(t, ast.Attribute):
            base = self._base(t)
            if base is not None:
                direct = isinstance(t.value, ast.Name)
                self.emit("mut", base, t.lineno, mkind if direct else None)
        elif isinstance(t, ast.Subscript):
            self.expr(t.slice)
            base = self._base(t)
            if base is not None:
                self.emit("mut", base, t.lineno, "acq")
        elif isinstance(t, ast.Name):
            if t.id in self.g and t.id in self._globaldecl:
                self.emit("mut", t.id, t.lineno, mkind)

    # -- statements ----------------------------------------------------
    def stmts(self, body: Sequence[ast.stmt]) -> None:
        for s in body:
            self.stmt(s)

    @staticmethod
    def _assign_kind(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Constant):
            if value.value is True:
                return "acq"
            if value.value is False or value.value is None:
                return "rel"
        return None

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Expr):
            self.expr(s.value)
        elif isinstance(s, ast.Assign):
            self.expr(s.value)
            k = self._assign_kind(s.value)
            for t in s.targets:
                self.target(t, k)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.expr(s.value)
                self.target(s.target, self._assign_kind(s.value))
        elif isinstance(s, ast.AugAssign):
            self.expr(s.value)
            k = "acq" if isinstance(s.op, ast.Add) else (
                "rel" if isinstance(s.op, ast.Sub) else None)
            self.target(s.target, k)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Subscript):
                    self.expr(t.slice)
                    base = self._base(t)
                    if base is not None:
                        self.emit("mut", base, t.lineno, "rel")
        elif isinstance(s, ast.Return):
            self.expr(s.value)
        elif isinstance(s, (ast.If, ast.While)):
            self.expr(s.test, test=True)
            self.stmts(s.body)
            self.stmts(s.orelse)
        elif isinstance(s, ast.For):
            self.expr(s.iter)
            self.stmts(s.body)
            self.stmts(s.orelse)
        elif isinstance(s, ast.AsyncFor):
            self.expr(s.iter)
            self.emit("await", None, s.lineno)
            self.stmts(s.body)
            self.stmts(s.orelse)
        elif isinstance(s, ast.With):
            for it in s.items:
                self.expr(it.context_expr)
            self.stmts(s.body)
        elif isinstance(s, ast.AsyncWith):
            lockish = False
            for it in s.items:
                self.expr(it.context_expr)
                lockish = lockish or self._lockish(it.context_expr)
            # __aenter__ awaits BEFORE the lock is held: a test made
            # before this line is stale by the time the body runs
            self.emit("await", None, s.lineno)
            if lockish:
                self._region += 1
                self.lock = self.lock + (self._region,)
            self.stmts(s.body)
            if lockish:
                self.lock = self.lock[:-1]
        elif isinstance(s, ast.Try):
            self._tryid += 1
            tid = self._tryid
            self.tries = self.tries + ((tid, "body"),)
            self.stmts(s.body)
            self.tries = self.tries[:-1]
            for h in s.handlers:
                self.tries = self.tries + ((tid, "handler"),)
                self.stmts(h.body)
                self.tries = self.tries[:-1]
            self.tries = self.tries + ((tid, "orelse"),)
            self.stmts(s.orelse)
            self.tries = self.tries[:-1]
            self.tries = self.tries + ((tid, "finally"),)
            self.stmts(s.finalbody)
            self.tries = self.tries[:-1]
        elif isinstance(s, ast.Assert):
            self.expr(s.test, test=True)
            self.expr(s.msg)
        elif isinstance(s, ast.Raise):
            self.expr(s.exc)
            self.expr(s.cause)
        elif isinstance(s, ast.Global):
            self._globaldecl.update(s.names)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            pass  # nested defs run in their own timeline
        else:
            # Pass/Break/Continue/Import/Nonlocal/Match fallback: walk
            # any expression children for reads, any stmt lists in order
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.stmt):
                    self.stmt(child)
                elif isinstance(child, ast.expr):
                    self.expr(child)


def _detect_check_then_act(
    ev: List[tuple], rel: str, qual: str
) -> List[Tuple[str, str, int, str]]:
    """-> [(attr, tag, line, msg)] for hazard shape 1."""
    out = []
    last: Dict[str, Optional[dict]] = {}
    fired: Set[str] = set()
    for kind, attr, line, lock, _tries, _mk in ev:
        if kind == "await":
            for t in last.values():
                if t is None or t["awaited"]:
                    continue
                held = t["lock"]
                # still holding the lock the test was made under?
                if held and lock[: len(held)] == held:
                    continue
                t["awaited"] = True
                t["await_line"] = line
        elif kind == "test" and attr is not None:
            last[attr] = {"line": line, "lock": lock, "awaited": False,
                          "await_line": 0}
        elif kind == "mut" and attr is not None:
            t = last.get(attr)
            if t is not None and t["awaited"] and attr not in fired:
                fired.add(attr)
                out.append((
                    attr, "ctw", line,
                    f"check-then-act on {attr} spans a yield point: "
                    f"tested at line {t['line']}, awaited at line "
                    f"{t['await_line']}, mutated here — another task "
                    f"can mutate {attr} inside the window. Re-check "
                    "after the await, hold one lock across the whole "
                    "window, or snapshot into a local before awaiting",
                ))
                last[attr] = None
    return out


def _detect_marker_leak(
    ev: List[tuple], rel: str, qual: str
) -> List[Tuple[str, str, int, str]]:
    """-> [(attr, tag, line, msg)] for hazard shape 2."""
    out = []
    acq: Dict[str, List[tuple]] = {}
    rel_: Dict[str, List[tuple]] = {}
    aws: List[tuple] = []
    for i, (kind, attr, line, _lock, tries, mk) in enumerate(ev):
        if kind == "await":
            aws.append((i, line, tries))
        elif kind == "mut" and attr is not None:
            if mk == "acq":
                acq.setdefault(attr, []).append((i, line, tries))
            elif mk == "rel":
                rel_.setdefault(attr, []).append((i, line, tries))
    for attr in sorted(set(acq) & set(rel_)):
        # tries whose finally releases this attr put the release on the
        # cancellation path for every await inside their body
        protected = {
            tid for _i, _l, tries in rel_[attr]
            for tid, sec in tries if sec == "finally"
        }
        found = False
        for ai, aline, a_tries in acq[attr]:
            if found:
                break
            if any(sec == "finally" for _t, sec in a_tries):
                continue  # acquire on a teardown path: not a marker
            for ri, rline, _r_tries in rel_[attr]:
                if ri <= ai:
                    continue
                between = [w for w in aws if ai < w[0] < ri]
                if not between:
                    continue
                unprot = [
                    w for w in between
                    if not any(tid in protected and sec != "finally"
                               for tid, sec in w[2])
                ]
                if unprot:
                    out.append((
                        attr, "leak", aline,
                        f"window marker on {attr} can leak on "
                        f"cancellation: acquired here, awaited at line "
                        f"{unprot[0][1]}, released at line {rline} "
                        "with no try/finally putting the release on "
                        "the cancellation path — a cancelled await "
                        "leaks the marker forever",
                    ))
                    found = True
                break  # only pair with the FIRST release after acquire
    return out


def _async_functions(tree: ast.Module):
    """Yield (qualname, AsyncFunctionDef) for every async def,
    including nested ones (each analyzed as its own timeline)."""

    def walk(node: ast.AST, scope: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, scope + [child.name])
            elif isinstance(child, ast.AsyncFunctionDef):
                q = ".".join(scope + [child.name])
                yield q, child
                yield from walk(child, scope + [child.name])
            elif isinstance(child, ast.FunctionDef):
                yield from walk(child, scope + [child.name])
            else:
                yield from walk(child, scope)

    yield from walk(tree, [])


def analyze_race_tree(tree: ast.Module, rel: str) -> List[Finding]:
    mutable_globals = module_mutable_globals(tree)
    findings: List[Finding] = []
    counts: Dict[Tuple[str, str, str], int] = {}
    for qual, fn in _async_functions(tree):
        scan = _RaceScan(mutable_globals)
        scan.stmts(fn.body)
        raws = _detect_check_then_act(scan.ev, rel, qual)
        raws += _detect_marker_leak(scan.ev, rel, qual)
        for attr, tag, line, msg in raws:
            n = counts.get((qual, attr, tag), 0)
            counts[(qual, attr, tag)] = n + 1
            findings.append(Finding(
                path=rel, line=line, rule=R_RACE, msg=f"[{qual}] {msg}",
                key=f"{R_RACE}:{rel}:{qual}:{attr}:{tag}{n}",
            ))
    return findings


def analyze_race_source(src: str, rel: str) -> List[Finding]:
    return analyze_race_tree(ast.parse(src, filename=rel), rel)


def rule_race(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    out: List[Finding] = []
    for rel in sorted(trees):
        if rel.startswith("dml_tpu/"):
            out.extend(analyze_race_tree(trees[rel], rel))
    return out


# ----------------------------------------------------------------------
# drift-wire-payloads
# ----------------------------------------------------------------------

WIRE_REL = "dml_tpu/cluster/wire.py"
INTRODUCER_REL = "dml_tpu/cluster/introducer.py"

#: call names whose awaited result is the reply payload dict
REQUEST_FNS = {"request", "leader_request", "leader_retry", "_leader_retry"}
#: call names that are definitely sends even with an unresolvable payload
SEND_FNS = REQUEST_FNS | {"send", "send_unique", "Message"}
#: wrapper senders whose real payload is composed INSIDE the wrapper
#: (tiered degradation): their call sites are always opaque sends —
#: the dict literal at the call site is only a fragment of the frame
OPAQUE_SEND_FNS = {"_send_metrics_tiered", "_send_trace_tiered"}
#: (rel, top-level qualname) whose send sites are deliberately
#: adversarial and excluded from schema inference
OFF_WIRE = {("dml_tpu/cluster/chaos.py", "fuzz_datagrams")}
#: the universal correlation key, excluded from every check
_RID = "rid"
#: success-discriminator keys: a reader probing one of these via .get
#: reads the rest of the payload conditionally (see assemble_contracts)
_DISCRIMINATORS = {"ok", "accepted", "done", "known"}

#: callee bases/names through which a payload dict cannot "escape"
#: into unseen reads (rendering/printing, builtins)
_BENIGN_CALLEES = {
    "print", "repr", "str", "len", "id", "type", "isinstance", "bool",
    "format", "sorted",
}
_BENIGN_CALL_BASES = {"log", "logging"}


@dataclass
class SendSite:
    rel: str
    line: int
    keys: Dict[str, bool]  # key -> always-sent
    open: bool


@dataclass
class ReadSet:
    required: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    optional: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    open: bool = False
    readers: int = 0

    def merge(self, other: "ReadSet") -> None:
        for k, loc in other.required.items():
            self.required.setdefault(k, loc)
        for k, loc in other.optional.items():
            self.optional.setdefault(k, loc)
        self.open = self.open or other.open
        self.readers += other.readers


@dataclass
class PayloadUsage:
    """Everything inference learned about the wire, pre-check."""

    sends: Dict[str, List[SendSite]] = field(default_factory=dict)
    handler_reads: Dict[str, ReadSet] = field(default_factory=dict)
    #: await-site reads keyed by the REQUEST member (resolved to its
    #: reply types through the payload map's `<- Q` annotations)
    await_reads: Dict[str, ReadSet] = field(default_factory=dict)
    aux_findings: List[Finding] = field(default_factory=list)


@dataclass
class MapEntry:
    required: Set[str]
    optional: Set[str]
    open: bool
    reply_to: Optional[str]
    line: int


_PAYLOAD_HEADER = "Payload map (lint-enforced)"
_PMAP_LINE = re.compile(r"^ {4}([A-Z][A-Z0-9_]*):\s*(.*)$")
_PMAP_CONT = re.compile(r"^ {6,}(\S.*)$")
_PMAP_KEY = re.compile(r"^[a-z_][a-z0-9_]*\??$")


def parse_payload_map(
    docstring: str, base_line: int = 1
) -> Optional[Tuple[Dict[str, MapEntry], List[Tuple[int, str]]]]:
    """-> ({member: MapEntry}, [(line, bad-token)]) or None when the
    section is absent. Token grammar per entry line: bare ``key`` =
    required, ``key?`` = optional, ``-`` = declared-empty, ``*`` =
    open payload, ``<- REQUEST`` = reply-of annotation."""
    lines = docstring.splitlines()
    try:
        start = next(i for i, ln in enumerate(lines)
                     if ln.strip() == _PAYLOAD_HEADER)
    except StopIteration:
        return None
    entries: Dict[str, MapEntry] = {}
    bad: List[Tuple[int, str]] = []
    current: Optional[str] = None
    in_list = False
    for i in range(start + 1, len(lines)):
        ln = lines[i]
        line_no = base_line + i
        m = _PMAP_LINE.match(ln)
        if m:
            in_list = True
            current = m.group(1)
            entries[current] = MapEntry(set(), set(), False, None, line_no)
            rest = m.group(2)
        elif in_list and current and _PMAP_CONT.match(ln):
            rest = _PMAP_CONT.match(ln).group(1)  # type: ignore[union-attr]
        else:
            if in_list and ln.strip() and not ln.startswith(" "):
                break  # next unindented section
            continue
        toks = rest.split()
        j = 0
        while j < len(toks):
            tok = toks[j]
            if tok == "<-" and j + 1 < len(toks):
                entries[current].reply_to = toks[j + 1]
                j += 2
                continue
            if tok == "-":
                pass
            elif tok == "*":
                entries[current].open = True
            elif _PMAP_KEY.match(tok):
                if tok.endswith("?"):
                    entries[current].optional.add(tok[:-1])
                else:
                    entries[current].required.add(tok)
            else:
                bad.append((line_no, tok))
            j += 1
    return entries, bad


# -- send-site / await-site collection ---------------------------------


class _DictState:
    __slots__ = ("keys", "open", "depth")

    def __init__(self, keys: Dict[str, bool], open_: bool, depth: int):
        self.keys = keys
        self.open = open_
        self.depth = depth


def _literal_dict_keys(node: ast.Dict) -> Tuple[Dict[str, bool], bool]:
    keys: Dict[str, bool] = {}
    open_ = False
    for k in node.keys:
        if k is None:  # **spread
            open_ = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys[k.value] = True
        else:
            open_ = True
    return keys, open_


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _as_msgtype(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "MsgType":
        return node.attr
    return None


def _msgtype_literals(call: ast.Call) -> List[str]:
    """MsgType members a call site can send: a direct ``MsgType.X``
    argument, or both arms of a ``MsgType.X if ok else MsgType.Y``
    conditional (the success/fail reply idiom)."""
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        m = _as_msgtype(a)
        if m is not None:
            return [m]
        if isinstance(a, ast.IfExp):
            arms = [_as_msgtype(a.body), _as_msgtype(a.orelse)]
            if all(arms):
                return [m for m in arms if m]
    return []


def _msgtype_literal(call: ast.Call) -> Optional[str]:
    ms = _msgtype_literals(call)
    return ms[0] if len(ms) == 1 else None


class _SendScan:
    """Per-function ordered scan: resolves local payload dicts, records
    send sites, and records await-request sites (for reply reads)."""

    def __init__(self, rel: str, usage: PayloadUsage) -> None:
        self.rel = rel
        self.usage = usage
        self.dicts: Dict[str, _DictState] = {}
        self.depth = 0
        #: [(request_member, bound var name | None, await node)]
        self.req_sites: List[Tuple[str, Optional[str], ast.Await]] = []

    # -- helpers -------------------------------------------------------
    def _payload_of(self, call: ast.Call) -> Optional[Tuple[Dict[str, bool], bool]]:
        cands = list(call.args) + [
            kw.value for kw in call.keywords if kw.arg == "data"
        ]
        for a in cands:
            if isinstance(a, ast.Dict):
                return _literal_dict_keys(a)
            if isinstance(a, ast.Name) and a.id in self.dicts:
                st = self.dicts[a.id]
                return dict(st.keys), st.open
        return None

    def _record_send(self, call: ast.Call, member: str) -> None:
        fname = _call_name(call.func)
        if fname == "register":
            return
        payload = None if fname in OPAQUE_SEND_FNS else self._payload_of(call)
        if payload is None:
            if fname not in SEND_FNS | OPAQUE_SEND_FNS:
                return  # MsgType used as a value, not a send
            keys: Dict[str, bool] = {}
            open_ = True
        else:
            keys, open_ = payload
        keys.pop(_RID, None)
        self.usage.sends.setdefault(member, []).append(
            SendSite(self.rel, call.lineno, keys, open_))

    def _maybe_send(self, call: ast.Call) -> None:
        for member in _msgtype_literals(call):
            self._record_send(call, member)

    def _dict_mutation(self, node: ast.AST) -> None:
        """Track ``d["k"] = v`` / ``d.update({...})`` / ``d.pop`` on
        locals bound to dict literals."""
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Subscript):
            t = node.targets[0]
            if isinstance(t.value, ast.Name) and t.value.id in self.dicts:
                st = self.dicts[t.value.id]
                sl = t.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    always = self.depth == st.depth
                    st.keys[sl.value] = st.keys.get(sl.value, False) or always
                else:
                    st.open = True
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            f = node.func
            if isinstance(f.value, ast.Name) and f.value.id in self.dicts:
                st = self.dicts[f.value.id]
                if f.attr == "update":
                    if node.args and isinstance(node.args[0], ast.Dict):
                        ks, op = _literal_dict_keys(node.args[0])
                        always = self.depth == st.depth
                        for k in ks:
                            st.keys[k] = st.keys.get(k, False) or always
                        st.open = st.open or op
                    elif node.args:
                        st.open = True
                    for kw in node.keywords:
                        if kw.arg:
                            st.keys[kw.arg] = st.keys.get(kw.arg, False) or \
                                (self.depth == st.depth)
                        else:
                            st.open = True
                elif f.attr == "pop" and node.args and \
                        isinstance(node.args[0], ast.Constant):
                    k = node.args[0].value
                    if isinstance(k, str) and k in st.keys:
                        st.keys[k] = False

    # -- traversal -----------------------------------------------------
    def expr(self, node: Optional[ast.AST]) -> None:
        if node is None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Call):
            self._dict_mutation(node)
            self._maybe_send(node)
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            member = _msgtype_literal(node.value)
            if member is not None and \
                    _call_name(node.value.func) in REQUEST_FNS:
                self.req_sites.append((member, None, node))
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def stmts(self, body: Sequence[ast.stmt]) -> None:
        for s in body:
            self.stmt(s)

    def _nested(self, *groups: Sequence[ast.stmt]) -> None:
        self.depth += 1
        for g in groups:
            self.stmts(g)
        self.depth -= 1

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            self.expr(s.value)
            self._dict_mutation(s)
            if len(s.targets) == 1 and isinstance(s.targets[0], ast.Name):
                name = s.targets[0].id
                v = s.value
                if isinstance(v, ast.Dict):
                    ks, op = _literal_dict_keys(v)
                    self.dicts[name] = _DictState(ks, op, self.depth)
                elif isinstance(v, ast.Call) and _call_name(v.func) == "dict":
                    ks = {kw.arg: True for kw in v.keywords if kw.arg}
                    op = bool(v.args) or any(kw.arg is None for kw in v.keywords)
                    self.dicts[name] = _DictState(ks, op, self.depth)
                elif isinstance(v, ast.Await) and isinstance(v.value, ast.Call) \
                        and _msgtype_literal(v.value) is not None \
                        and _call_name(v.value.func) in REQUEST_FNS:
                    # bind the reply var: drop the anonymous site just
                    # recorded by expr() and re-record with the name
                    if self.req_sites and self.req_sites[-1][2] is v:
                        member = self.req_sites[-1][0]
                        self.req_sites[-1] = (member, name, v)
                    self.dicts.pop(name, None)
                else:
                    self.dicts.pop(name, None)
        elif isinstance(s, (ast.If, ast.While)):
            self.expr(s.test)
            self._nested(s.body, s.orelse)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self.expr(s.iter)
            self._nested(s.body, s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for it in s.items:
                self.expr(it.context_expr)
            self.stmts(s.body)  # with-bodies always run: same depth
        elif isinstance(s, ast.Try):
            self._nested(s.body, s.orelse)
            for h in s.handlers:
                self._nested(h.body)
            self.stmts(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            pass
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.stmt):
                    self.stmt(child)
                elif isinstance(child, ast.expr):
                    self.expr(child)


# -- read collection ---------------------------------------------------


class _FnIndex:
    """Where to find a callee for one-call-deep delegation."""

    def __init__(self, trees: Dict[str, ast.Module]) -> None:
        self.methods: Dict[Tuple[str, str], Tuple[str, ast.AST]] = {}
        self.module_fns: Dict[Tuple[str, str], ast.AST] = {}
        for rel in sorted(trees):
            if not rel.startswith("dml_tpu/"):
                continue
            tree = trees[rel]
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.module_fns[(rel, node.name)] = node
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self.methods.setdefault(
                                (node.name, sub.name), (rel, sub))


def _parent_map(fn: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


class _ReadCollector:
    """Classify every use of a message/payload variable inside one
    function into required/optional reads, following ``d = msg.data``
    aliases and one level of resolvable delegation."""

    MAX_DEPTH = 4

    def __init__(self, index: _FnIndex) -> None:
        self.index = index
        # one collector serves the whole package scan: handlers that
        # delegate into shared helpers (and functions hosting several
        # await sites) would otherwise rebuild the same parent map
        self._pmaps: Dict[int, Dict[ast.AST, ast.AST]] = {}

    def _parents_of(self, fn: ast.AST) -> Dict[ast.AST, ast.AST]:
        pm = self._pmaps.get(id(fn))
        if pm is None:
            pm = self._pmaps[id(fn)] = _parent_map(fn)
        return pm

    def collect(
        self,
        rel: str,
        fn: ast.AST,
        params: Dict[str, str],  # name -> "msg" | "data"
        class_name: Optional[str],
        depth: int = 0,
        visited: Optional[Set[Tuple[int, str]]] = None,
    ) -> ReadSet:
        rs = ReadSet(readers=1 if depth == 0 else 0)
        if depth > self.MAX_DEPTH:
            rs.open = True
            return rs
        visited = visited or set()
        parents = self._parents_of(fn)
        # follow aliases to fixpoint: d = msg.data; d2 = d
        kinds = dict(params)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                tname = node.targets[0].id
                if tname in kinds:
                    continue
                v = node.value
                if isinstance(v, ast.Name) and kinds.get(v.id):
                    kinds[tname] = kinds[v.id]
                    changed = True
                elif (isinstance(v, ast.Attribute) and v.attr == "data"
                        and isinstance(v.value, ast.Name)
                        and kinds.get(v.value.id) == "msg"):
                    kinds[tname] = "data"
                    changed = True
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and node.id in kinds
                    and isinstance(node.ctx, ast.Load)):
                continue
            kind = kinds[node.id]
            target: ast.AST = node
            if kind == "msg":
                p = parents.get(node)
                if isinstance(p, ast.Attribute):
                    if p.attr == "data":
                        target = p  # classify the msg.data node below
                    else:
                        continue  # .sender/.type: not payload
                else:
                    self._classify_obj(rel, fn, node, parents, rs, kinds,
                                       class_name, depth, visited, is_msg=True)
                    continue
            self._classify_obj(rel, fn, target, parents, rs, kinds,
                               class_name, depth, visited, is_msg=False)
        return rs

    # -- classification of one payload-dict expression node ------------
    def _classify_obj(
        self, rel, fn, node, parents, rs: ReadSet, kinds, class_name,
        depth, visited, is_msg: bool,
    ) -> None:
        p = parents.get(node)
        loc = (rel, getattr(node, "lineno", 1))
        if isinstance(p, ast.Subscript) and p.value is node:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                return  # handler writes into the dict: not a read
            sl = p.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                if sl.value != _RID:
                    rs.required.setdefault(sl.value, loc)
            else:
                rs.open = True
            return
        if isinstance(p, ast.Attribute) and p.value is node:
            meth = p.attr
            call = parents.get(p)
            if isinstance(call, ast.Call) and call.func is p:
                if meth in ("get", "pop", "setdefault"):
                    a0 = call.args[0] if call.args else None
                    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                        if a0.value != _RID:
                            rs.optional.setdefault(a0.value, loc)
                    else:
                        rs.open = True
                elif meth in ("items", "keys", "values", "copy", "update"):
                    rs.open = True  # iterates/clones everything
                else:
                    rs.open = True
                return
            rs.open = True
            return
        if isinstance(p, ast.Compare) and node in p.comparators:
            # "k" in d  (presence probe: an optional read)
            if len(p.ops) == 1 and isinstance(p.ops[0], (ast.In, ast.NotIn)) \
                    and isinstance(p.left, ast.Constant) \
                    and isinstance(p.left.value, str):
                if p.left.value != _RID:
                    rs.optional.setdefault(p.left.value, loc)
            return
        if isinstance(p, (ast.BoolOp, ast.UnaryOp, ast.IfExp)):
            return  # truthiness only
        if isinstance(p, (ast.If, ast.While, ast.Assert)):
            return  # bare `if d:` truthiness
        if isinstance(p, (ast.FormattedValue, ast.JoinedStr)):
            return  # rendered into a string
        if isinstance(p, ast.Call) and (node in p.args or any(
                kw.value is node for kw in p.keywords)):
            self._delegate(rel, fn, p, node, rs, class_name, depth,
                           visited, is_msg)
            return
        if (isinstance(p, ast.Assign) and len(p.targets) == 1
                and isinstance(p.targets[0], ast.Name)
                and p.targets[0].id in kinds):
            return  # the tracked-alias binding itself (d = msg.data)
        rs.open = True  # stored/returned/iterated: flows out of sight

    def _delegate(
        self, rel, fn, call: ast.Call, arg_node, rs: ReadSet, class_name,
        depth, visited, is_msg: bool,
    ) -> None:
        f = call.func
        fname = _call_name(f)
        if fname in _BENIGN_CALLEES:
            return
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in _BENIGN_CALL_BASES:
            return
        callee: Optional[Tuple[str, ast.AST]] = None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and class_name:
            callee = self.index.methods.get((class_name, f.attr))
            callee_class = class_name
        elif isinstance(f, ast.Name):
            target = self.index.module_fns.get((rel, f.id))
            callee = (rel, target) if target is not None else None
            callee_class = None
        else:
            callee_class = None
        if callee is None:
            rs.open = True
            return
        crel, cfn = callee
        # map the argument position/keyword onto the callee parameter
        args = cfn.args.args  # type: ignore[attr-defined]
        names = [a.arg for a in args]
        if names and names[0] == "self":
            names = names[1:]
        pname: Optional[str] = None
        for i, a in enumerate(call.args):
            if a is arg_node and i < len(names):
                pname = names[i]
        for kw in call.keywords:
            if kw.value is arg_node and kw.arg:
                pname = kw.arg
        if pname is None:
            rs.open = True
            return
        vkey = (id(cfn), pname)
        if vkey in visited:
            return
        visited.add(vkey)
        sub = self.collect(
            crel, cfn, {pname: "msg" if is_msg else "data"},
            callee_class, depth + 1, visited,
        )
        rs.merge(sub)


# -- whole-package usage collection ------------------------------------


def _functions_with_quals(tree: ast.Module):
    """(top-level qualname, class name or None, fn) for every def."""

    def walk(node, top, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, top, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                t = top if top is not None else child.name
                yield t, cls, child
                yield from walk(child, t, cls)
            else:
                yield from walk(child, top, cls)

    yield from walk(tree, None, None)


def collect_payload_usage(
    trees: Dict[str, ast.Module],
    members: Dict[str, int],
    reply_map: Dict[str, List[str]],
) -> PayloadUsage:
    usage = PayloadUsage()
    index = _FnIndex(trees)
    collector = _ReadCollector(index)

    # 1) send sites + await-request sites
    for rel in sorted(trees):
        if not rel.startswith("dml_tpu/"):
            continue
        for top_qual, cls, fn in _functions_with_quals(trees[rel]):
            if (rel, top_qual) in OFF_WIRE:
                continue
            # nested defs are skipped by the scanner and arrive as
            # their own (top_qual, fn) pairs from _functions_with_quals
            scan = _SendScan(rel, usage)
            scan.stmts(fn.body)
            # reply reads per await-request site
            for member, var, await_node in scan.req_sites:
                rs = ReadSet(readers=1)
                if var is not None:
                    rs.merge(collector.collect(rel, fn, {var: "data"}, cls))
                    rs.readers = 1
                else:
                    parents = collector._parents_of(fn)
                    p = parents.get(await_node)
                    loc = (rel, await_node.lineno)
                    if isinstance(p, ast.Expr):
                        pass  # reply discarded: reads nothing
                    elif isinstance(p, ast.Subscript) and isinstance(
                            p.slice, ast.Constant) and isinstance(
                            p.slice.value, str):
                        if p.slice.value != _RID:
                            rs.required.setdefault(p.slice.value, loc)
                    elif isinstance(p, ast.Attribute) and p.attr == "get":
                        call = parents.get(p)
                        if isinstance(call, ast.Call) and call.args and \
                                isinstance(call.args[0], ast.Constant) and \
                                isinstance(call.args[0].value, str):
                            if call.args[0].value != _RID:
                                rs.optional.setdefault(call.args[0].value, loc)
                        else:
                            rs.open = True
                    else:
                        rs.open = True  # returned / forwarded
                if member not in reply_map and (
                        rs.required or rs.optional or rs.open):
                    usage.aux_findings.append(Finding(
                        path=rel, line=await_node.lineno, rule=R_PAYLOAD,
                        msg=f"await-site reads MsgType.{member}'s reply "
                            "payload but the wire.py payload map declares "
                            f"no reply type for it (missing `<- {member}` "
                            "annotation) — the reply schema cannot be "
                            "checked",
                        key=f"{R_PAYLOAD}:unannotated-reply:{member}",
                    ))
                if member not in usage.await_reads:
                    usage.await_reads[member] = rs
                else:
                    usage.await_reads[member].merge(rs)

    # 2) handler reads via registrations + HANDLER_OWNERS
    regs: List[Tuple[str, str, str, int, str]] = []
    for rel in sorted(trees):
        if rel.startswith("dml_tpu/"):
            for member, cls, handler, line in extract_registrations(
                    trees[rel], rel):
                regs.append((member, cls, handler, line, rel))
    for member, cls, handler, _line, rel in regs:
        if member not in members:
            continue
        found = index.methods.get((cls, handler))
        if found is None:
            continue
        hrel, hfn = found
        args = [a.arg for a in hfn.args.args]
        if len(args) < 2:
            continue
        msg_param = args[1]  # (self, msg, addr)
        rs = collector.collect(hrel, hfn, {msg_param: "msg"}, cls)
        if member not in usage.handler_reads:
            usage.handler_reads[member] = rs
        else:
            usage.handler_reads[member].merge(rs)
    return usage


# -- the check ---------------------------------------------------------


def _reply_map_from_pmap(
    pmap: Optional[Dict[str, MapEntry]]
) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for member, e in (pmap or {}).items():
        if e.reply_to:
            out.setdefault(e.reply_to, []).append(member)
    for v in out.values():
        v.sort()
    return out


@dataclass
class Contract:
    """The inferred wire contract for one MsgType member."""

    sends: List[SendSite]
    ever: Set[str]       # keys any sender ships
    opaque: bool         # some send site is unresolvable
    rs: ReadSet          # merged reads (handler + routed await sites)
    soft: Set[str]       # await-required keys of multi-reply requests
    required: Set[str]   # the contract: reader indexes unconditionally
    optional: Set[str]   # everything else on the wire
    open: bool           # inference cannot claim completeness


def assemble_contracts(
    members: Dict[str, int],
    usage: PayloadUsage,
    reply_map: Dict[str, List[str]],
) -> Dict[str, Contract]:
    """One inference result per member — shared by the checker and the
    map dumper so the enforced contract and the documented one can
    never diverge in derivation."""
    reads: Dict[str, ReadSet] = {}
    for member, rs in usage.handler_reads.items():
        reads.setdefault(member, ReadSet()).merge(rs)
    multi: Dict[str, Set[str]] = {}
    for req, rs in usage.await_reads.items():
        targets = reply_map.get(req, [])
        for t in targets:
            reads.setdefault(t, ReadSet()).merge(rs)
            if len(targets) > 1:
                multi.setdefault(t, set()).update(rs.required)
    out: Dict[str, Contract] = {}
    for member in members:
        sends = usage.sends.get(member, [])
        rs = reads.get(member, ReadSet())
        # discriminated-union demotion: a key the reader ALSO probes
        # via .get()/`in` is guarded somewhere — the bare index is not
        # an unconditional contract on every sender
        for k in list(rs.required):
            if k in rs.optional:
                del rs.required[k]
        # discriminator-gated reader: a reader that consults a success
        # flag (`if not reply.get("ok"): ...`) indexes the rest of the
        # payload conditionally — an error-shaped reply legitimately
        # omits the success fields, so nothing stays REQUIRED of every
        # sender
        if set(rs.optional) & _DISCRIMINATORS:
            for k in list(rs.required):
                rs.optional.setdefault(k, rs.required.pop(k))
        ever: Set[str] = set()
        for site in sends:
            ever.update(site.keys)
        opaque = any(site.open for site in sends)
        soft = multi.get(member, set())
        required = set(rs.required) - soft
        optional = (ever | set(rs.optional) | soft) - required - {_RID}
        # no visible sender at all = open too: the keys an unseen
        # sender ships cannot be enumerated
        open_ = opaque or rs.open or not sends
        out[member] = Contract(sends, ever, opaque, rs, soft,
                               required, optional, open_)
    return out


def check_payloads(
    members: Dict[str, int],
    usage: PayloadUsage,
    pmap: Optional[Dict[str, MapEntry]],
    map_errors: List[Tuple[int, str]],
    wire_rel: str = WIRE_REL,
) -> List[Finding]:
    fs: List[Finding] = list(usage.aux_findings)

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_PAYLOAD, msg=msg,
                          key=f"{R_PAYLOAD}:{subject}"))

    if pmap is None:
        f(wire_rel, 1, "no-map",
          f"wire.py's module docstring has no '{_PAYLOAD_HEADER}' "
          "section — the per-MsgType payload contracts must be declared "
          "where the linter (and the reader) can see them")
        pmap = {}
    for line, tok in map_errors:
        f(wire_rel, line, f"map-syntax:{tok}",
          f"payload map token {tok!r} is neither a key, 'key?', '-', "
          "'*', nor a '<- REQUEST' annotation")
    reply_map = _reply_map_from_pmap(pmap)
    contracts = assemble_contracts(members, usage, reply_map)

    for member in sorted(members):
        c = contracts[member]
        sends, rs, ever, soft = c.sends, c.rs, c.ever, c.soft

        # required-read-but-never-sent: the latent KeyError
        if sends and not c.opaque:
            for k in sorted(c.required - ever):
                loc = rs.required[k]
                f(loc[0], loc[1], f"required-never-sent:{member}:{k}",
                  f"MsgType.{member}'s reader indexes payload key {k!r} "
                  "unconditionally but no sender of the type ever ships "
                  "it — a latent KeyError on the wire")
        # conditional-send / sender disagreement vs a required read
        for k in sorted(c.required):
            for site in sends:
                if site.open:
                    continue
                if k not in site.keys:
                    if k in ever:  # another sender ships it: disagreement
                        f(site.rel, site.line,
                          f"required-not-always:{member}:{k}:{site.rel}:{site.line}",
                          f"this sender of MsgType.{member} never ships "
                          f"{k!r} but the type's reader indexes it "
                          "unconditionally (other senders do ship it) — "
                          "senders disagree on the contract")
                elif not site.keys[k]:
                    f(site.rel, site.line,
                      f"required-not-always:{member}:{k}:{site.rel}:{site.line}",
                      f"this sender of MsgType.{member} ships {k!r} only "
                      "conditionally but the type's reader indexes it "
                      "unconditionally — a skipped branch is a KeyError "
                      "at the reader")
        # sent-but-never-read: dead wire bytes
        if rs.readers and not rs.open:
            for k in sorted(ever - set(rs.required) - set(rs.optional)
                            - soft):
                site = next(s for s in sends if k in s.keys)
                f(site.rel, site.line, f"sent-never-read:{member}:{k}",
                  f"MsgType.{member} ships payload key {k!r} but no "
                  "reader of the type ever looks at it — dead wire "
                  "bytes (drop it, or the reader lost a field)")

        # map cross-check (both directions)
        entry = pmap.get(member)
        if not pmap and member not in pmap:
            continue  # no map at all: already reported
        if entry is None:
            f(wire_rel, members[member], f"unmapped:{member}",
              f"MsgType.{member} has no payload-map line — every member "
              "must declare its payload contract (use '-' for empty, "
              "'*' for open)")
            continue
        contract_required = c.required
        contract_optional = c.optional
        analysis_open = c.open
        known = contract_required | contract_optional
        mapped = entry.required | entry.optional
        if entry.open:
            if not analysis_open:
                f(wire_rel, entry.line, f"map-open-resolved:{member}",
                  f"payload map marks MsgType.{member} open ('*') but "
                  "inference fully resolves every sender and reader — "
                  "declare the real contract")
            for k in sorted(known - mapped):
                f(wire_rel, entry.line, f"map-missing-key:{member}:{k}",
                  f"payload key {k!r} of MsgType.{member} is on the wire "
                  "but missing from the payload map")
            for k in sorted(contract_required - entry.required):
                if k in mapped:
                    f(wire_rel, entry.line, f"map-requiredness:{member}:{k}",
                      f"payload key {k!r} of MsgType.{member} is read "
                      "unconditionally (required) but the map marks it "
                      "optional")
        else:
            if analysis_open:
                f(wire_rel, entry.line, f"map-not-open:{member}",
                  f"MsgType.{member}'s payload cannot be fully resolved "
                  "(opaque sender or open reader) but the map does not "
                  "mark it '*' — the declared contract overclaims")
                continue
            for k in sorted(mapped - known):
                f(wire_rel, entry.line, f"map-key-unknown:{member}:{k}",
                  f"payload map lists key {k!r} for MsgType.{member} but "
                  "nothing on the wire sends or reads it — stale map "
                  "entry")
            for k in sorted(known - mapped):
                f(wire_rel, entry.line, f"map-missing-key:{member}:{k}",
                  f"payload key {k!r} of MsgType.{member} is on the wire "
                  "but missing from the payload map")
            for k in sorted((contract_required & mapped) - entry.required):
                f(wire_rel, entry.line, f"map-requiredness:{member}:{k}",
                  f"payload key {k!r} of MsgType.{member} is read "
                  "unconditionally (required) but the map marks it "
                  "optional")
            for k in sorted((contract_optional & mapped) & entry.required):
                f(wire_rel, entry.line, f"map-requiredness:{member}:{k}",
                  f"payload key {k!r} of MsgType.{member} is marked "
                  "required in the map but no reader indexes it "
                  "unconditionally")
    for member, entry in sorted(pmap.items()):
        if member not in members:
            f(wire_rel, entry.line, f"map-ghost:{member}",
              f"payload map declares MsgType.{member} which is not an "
              "enum member")
        if entry.reply_to and entry.reply_to not in members:
            f(wire_rel, entry.line, f"map-ghost-reply:{member}",
              f"payload map annotates MsgType.{member} as the reply of "
              f"{entry.reply_to}, which is not an enum member")
    return fs


def run_payload_check(
    trees: Dict[str, ast.Module], wire_rel: str = WIRE_REL
) -> List[Finding]:
    """Pure driver over parsed trees (fixture-friendly)."""
    if wire_rel not in trees:
        return []
    wire_tree = trees[wire_rel]
    members = extract_msgtype_members(wire_tree)
    if not members:
        return []
    doc = ast.get_docstring(wire_tree) or ""
    parsed = parse_payload_map(doc)
    if parsed is None:
        pmap, map_errors = None, []
    else:
        pmap, map_errors = parsed
    usage = collect_payload_usage(
        trees, members, _reply_map_from_pmap(pmap))
    return check_payloads(members, usage, pmap, map_errors, wire_rel)


def rule_payloads(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    return run_payload_check(trees)


# ----------------------------------------------------------------------
# map bootstrap helper (contributor tool, not part of the lint run)
# ----------------------------------------------------------------------


def dump_inferred_map(trees: Dict[str, ast.Module]) -> List[str]:
    """Render the inferred contract as payload-map lines — the seed for
    (and the way to refresh) wire.py's docstring section."""
    wire_tree = trees.get(WIRE_REL)
    if wire_tree is None:
        return []
    members = extract_msgtype_members(wire_tree)
    doc = ast.get_docstring(wire_tree) or ""
    parsed = parse_payload_map(doc)
    pmap = parsed[0] if parsed else {}
    reply_map = _reply_map_from_pmap(pmap)
    usage = collect_payload_usage(trees, members, reply_map)
    contracts = assemble_contracts(members, usage, reply_map)
    lines = []
    for member in sorted(members, key=lambda m: members[m]):
        c = contracts[member]
        toks = sorted(c.required) + [f"{k}?" for k in sorted(c.optional)]
        if c.open:
            toks.append("*")
        if not toks:
            toks = ["-"]
        entry = pmap.get(member)
        if entry is not None and entry.reply_to:
            toks += ["<-", entry.reply_to]
        lines.append(f"    {member}: " + " ".join(toks))
    return lines


if __name__ == "__main__":  # pragma: no cover - contributor helper
    import os
    import sys

    from .dmllint import repo_root, scan_paths, _parse, _rel

    root = sys.argv[1] if len(sys.argv) > 1 else repo_root()
    trees = {}
    for path in scan_paths(root):
        rel = _rel(root, path)
        trees[rel] = _parse(path, rel)
    print("\n".join(dump_inferred_map(trees)))
