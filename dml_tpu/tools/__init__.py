"""Operational tools: parity checks, weight acquisition, diagnostics."""
