"""Analytical conv roofline: why each CNN's MFU ceiling sits where it
does on TPU (VERDICT r2 item 3 — "explain the roofline" evidence).

For every `conv_general_dilated` in a model's traced jaxpr, viewed as
a matmul (M = N·Ho·Wo output rows, K = kh·kw·Cin, Nc = Cout):

- **MXU term**: the 128x128 systolic array pads K and Nc to 128
  lanes; tile utilization = (K/K_pad)·(Nc/Nc_pad). Inception's odd
  branch widths (48, 96, 80...) pad badly — its flop-weighted tile
  utilization is ~0.69 vs ResNet50's ~0.89. That alone caps MFU.
- **HBM term**: bytes(input + weights + output at bf16) / stream
  bandwidth. Depthwise convs (feature_group_count = C) never touch
  the MXU — they are pure VPU streams, so their time is entirely this
  term. EfficientNet's depthwise stages carry ~7% of its FLOPs but a
  large share of its wall time.

Per conv, time = max(MXU, HBM) (no overlap assumed within a conv);
summing gives a **pessimistic** serial roofline, while
max(sum MXU, sum HBM) gives an **optimistic** perfectly-pipelined
one. Measured MFU should land between the implied bounds — if it
sits below the pessimistic bound, something is actually wrong (a
layout/algorithm problem), not "the architecture".

**Round-4 revision (VERDICT r3 item 8):** the single 750 GB/s stream
constant is wrong for EfficientNet's access patterns. Microbenched on
the v5e (``--microbench``, slope-timed isolated convs at B4's own
shapes): depthwise convs achieve 120–360 GB/s, dense 1x1s 250–570,
scaling with working-set size — no B4 conv class comes near 750.
``mfu_bound_serial_measured_bw`` recomputes the serial bound with the
measured per-class bandwidths; for B4 b128 that bound is ~0.062 and
the isolated sum-of-parts measurement (55 unique conv shapes, counted)
is 179.8 ms → 0.032, while the FUSED forward measures 50.9 ms → 0.112
conv-MFU. I.e. the fused model is 3.5x faster than its parts: XLA's
fusion/overlap already exceeds every serial bound computable from
measured per-op constants, and the r3 "gap to the 0.163 ceiling" was
an artifact of the optimistic bandwidth constant, not an
implementation gap. (b192/b256 were tried and do not beat b128:
0.085/0.110 vs 0.112.)

Run: ``python -m dml_tpu.tools.conv_roofline [model ...]``
(CPU-safe: only traces jaxprs, compiles nothing), or
``python -m dml_tpu.tools.conv_roofline --microbench [model]`` on the
chip to reproduce the measured sum-of-parts vs fused comparison.
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict

# measured stream bandwidth on this chip (~650-750 GB/s effective on
# the bench lm decode path, latest BENCH_r* artifact; spec 819)
HBM_BW = 750e9
PEAK = 197e12  # v5e dense bf16


def eff_bw(feature_group_count: int, spatial: int) -> float:
    """Per-class effective HBM bandwidth, measured on-chip with
    isolated slope-timed convs at EfficientNet-B4's own shapes
    (--microbench; 2026-07 v5e captures: dw 3x3 192ch@95^2 357 GB/s,
    dw 5x5 960ch@24^2 179, dw@12^2 122, dense 1x1 32->192@95^2 254,
    dense 1x1s@24^2 274-570). Coarse two-bucket model per class."""
    if feature_group_count > 1:  # depthwise: VPU window streams
        return 300e9 if spatial >= 48 else 150e9
    return 300e9 if spatial >= 95 else 420e9


def analyze(name: str, batch: int) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from ..models.params_io import init_variables
    from ..models.registry import get_model

    spec = get_model(name)
    v = init_variables(spec, dtype=jnp.bfloat16)
    model = spec.build(dtype=jnp.bfloat16)
    x = jnp.zeros((batch, *spec.input_size, 3), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(lambda v, x: model.apply(v, x, train=False))(v, x)

    tot_flops = mxu_flops = w_util = 0.0
    t_serial = t_mxu_sum = t_mem_sum = t_serial_meas = 0.0
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name != "conv_general_dilated":
            continue
        lhs = eqn.invars[0].aval
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        fg = eqn.params.get("feature_group_count", 1)
        kh, kw, cin_g, cout = rhs.shape  # HWIO
        n, ho, wo, _ = out.shape
        flops = 2.0 * n * ho * wo * kh * kw * cin_g * cout
        tot_flops += flops
        bytes_ = 2.0 * (
            math.prod(lhs.shape) + math.prod(rhs.shape) + math.prod(out.shape)
        )
        t_mem = bytes_ / HBM_BW
        t_mem_meas = bytes_ / eff_bw(fg, lhs.shape[1])
        t_mem_sum += t_mem
        if fg > 1:  # depthwise: VPU stream, no MXU work
            t_serial += t_mem
            t_serial_meas += t_mem_meas
            continue
        k_dim, n_dim = kh * kw * cin_g, cout
        util = (
            (k_dim / (math.ceil(k_dim / 128) * 128))
            * (n_dim / (math.ceil(n_dim / 128) * 128))
        )
        t_mxu = flops / (PEAK * util)
        mxu_flops += flops
        w_util += flops * util
        t_mxu_sum += t_mxu
        t_serial += max(t_mxu, t_mem)
        t_serial_meas += max(t_mxu, t_mem_meas)

    t_pipelined = max(t_mxu_sum, t_mem_sum)
    return {
        "model": name,
        "batch": batch,
        "conv_gflops": round(tot_flops / 1e9, 1),
        "mxu_flop_share": round(mxu_flops / tot_flops, 3),
        "tile_util_flop_weighted": round(w_util / max(mxu_flops, 1), 3),
        "mfu_bound_serial": round(tot_flops / PEAK / t_serial, 3),
        "mfu_bound_serial_measured_bw": round(
            tot_flops / PEAK / t_serial_meas, 3
        ),
        "mfu_bound_pipelined": round(tot_flops / PEAK / t_pipelined, 3),
        "roofline_ms_serial": round(t_serial * 1e3, 2),
        "roofline_ms_serial_measured_bw": round(t_serial_meas * 1e3, 2),
        "roofline_ms_pipelined": round(t_pipelined * 1e3, 2),
    }


def microbench(name: str = "EfficientNetB4", batch: int = 128) -> Dict[str, Any]:
    """On-chip evidence pass: slope-time every UNIQUE conv shape of the
    model in isolation, sum (weighted by occurrence count), and compare
    against the fused full forward. The fused/isolated ratio is the
    fusion-overlap factor that no per-op roofline can see — on B4 b128
    it measures ~3.5x, which is why the fused model BEATS every serial
    bound built from measured per-op constants."""
    import collections

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..benchmarks import device_seconds_per_iter, poke
    from ..models.params_io import init_variables
    from ..models.registry import get_model

    spec = get_model(name)
    v = init_variables(spec, dtype=jnp.bfloat16)
    model = spec.build(dtype=jnp.bfloat16)
    x0 = jnp.zeros((batch, *spec.input_size, 3), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(lambda v, x: model.apply(v, x, train=False))(v, x0)
    shapes: collections.Counter = collections.Counter()
    tot_flops = 0.0
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name != "conv_general_dilated":
            continue
        lhs = tuple(eqn.invars[0].aval.shape)
        rhs = tuple(eqn.invars[1].aval.shape)
        fg = eqn.params.get("feature_group_count", 1)
        st = tuple(eqn.params.get("window_strides"))
        pad = tuple(map(tuple, eqn.params.get("padding")))
        shapes[(lhs, rhs, fg, st, pad)] += 1
        kh, kw, cin_g, cout = rhs
        n, ho, wo, _ = eqn.outvars[0].aval.shape
        tot_flops += 2.0 * n * ho * wo * kh * kw * cin_g * cout
    t_parts = 0.0
    for (ls, rs, fg, st, pad), cnt in shapes.items():
        x = jnp.zeros(ls, jnp.bfloat16)
        w = jnp.zeros(rs, jnp.bfloat16)
        dn = lax.conv_dimension_numbers(ls, rs, ("NHWC", "HWIO", "NHWC"))

        def step(i, acc, x, w, fg=fg, st=st, dn=dn, pad=list(pad)):
            y = lax.conv_general_dilated(
                poke(x, acc), w, st, pad,
                feature_group_count=fg, dimension_numbers=dn,
            )
            return jnp.max(y.astype(jnp.float32))

        # reps>=3: with 2 samples _paired_slopes' "median" is the max,
        # which would bias every isolated timing slow (and inflate the
        # published fusion_overlap_factor)
        t_parts += device_seconds_per_iter(step, x, w, chains=(6, 24), reps=3) * cnt
    vars_dev = jax.device_put(v)
    fwd = jax.jit(lambda v, x: model.apply(v, x, train=False))

    def fstep(i, acc, v, x):
        return jnp.max(fwd(v, poke(x, acc)).astype(jnp.float32))

    t_fused = device_seconds_per_iter(fstep, vars_dev, x0, chains=(3, 10), reps=3)
    return {
        "model": name,
        "batch": batch,
        "unique_conv_shapes": len(shapes),
        "conv_gflops": round(tot_flops / 1e9, 1),
        "isolated_sum_ms": round(t_parts * 1e3, 1),
        "isolated_sum_mfu": round(tot_flops / PEAK / t_parts, 3),
        "fused_forward_ms": round(t_fused * 1e3, 1),
        "fused_conv_mfu": round(tot_flops / PEAK / t_fused, 3),
        "fusion_overlap_factor": round(t_parts / t_fused, 2),
    }


def _concat_shapes(name: str, batch: int):
    """(jaxpr concat inventory, conv tot_flops): every `concatenate`
    in the model's forward as (input_shapes, output_shape, dim) with
    occurrence counts, plus the conv FLOP total the bounds normalize
    by. CPU-safe (trace only)."""
    import collections

    import jax
    import jax.numpy as jnp

    from ..models.params_io import init_variables
    from ..models.registry import get_model

    spec = get_model(name)
    v = init_variables(spec, dtype=jnp.bfloat16)
    model = spec.build(dtype=jnp.bfloat16)
    x = jnp.zeros((batch, *spec.input_size, 3), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(lambda v, x: model.apply(v, x, train=False))(v, x)
    concats: collections.Counter = collections.Counter()
    tot_flops = 0.0
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name == "conv_general_dilated":
            rhs = eqn.invars[1].aval
            out_av = eqn.outvars[0].aval
            kh, kw, cin_g, cout = rhs.shape
            n, ho, wo, _ = out_av.shape
            tot_flops += 2.0 * n * ho * wo * kh * kw * cin_g * cout
        elif eqn.primitive.name == "concatenate":
            ins = tuple(tuple(iv.aval.shape) for iv in eqn.invars)
            concats[(
                ins, tuple(eqn.outvars[0].aval.shape),
                int(eqn.params.get("dimension", 0)),
            )] += 1
    return concats, tot_flops


def concat_analysis(name: str = "InceptionV3", batch: int = 128) -> Dict[str, Any]:
    """CPU-safe concat accounting (ROADMAP item, VERDICT r5 weak #5):
    the conv roofline treats each branch's output as free to
    materialize, but a branch CONCAT is a pure HBM copy — every input
    read + the fused tensor written, zero FLOPs. Folding those bytes
    (at the same stream-bandwidth constant the conv HBM terms use)
    into the serial roofline gives `mfu_bound_serial_with_concat`:
    the bound a concat-blind roofline overstates. The on-chip
    companion (`concat_microbench`) replaces the constant with
    isolated slope-timed concats at the model's own shapes."""
    concats, tot_flops = _concat_shapes(name, batch)
    base = analyze(name, batch)
    concat_bytes = 0.0
    n_concats = 0
    for (ins, out_shape, _dim), cnt in concats.items():
        per = 2.0 * (sum(math.prod(s) for s in ins) + math.prod(out_shape))
        concat_bytes += per * cnt
        n_concats += cnt
    t_concat = concat_bytes / HBM_BW
    t_serial = base["roofline_ms_serial"] / 1e3
    # zero concat traffic degenerates EXACTLY to the plain bound (the
    # reconstruction from the rounded ms field would drift a ulp)
    with_concat = (
        base["mfu_bound_serial"] if concat_bytes == 0
        else round(tot_flops / PEAK / (t_serial + t_concat), 3)
    )
    return {
        "model": name,
        "batch": batch,
        "concat_sites": n_concats,
        "concat_unique_shapes": len(concats),
        "concat_gbytes": round(concat_bytes / 1e9, 2),
        "concat_ms_at_stream_bw": round(t_concat * 1e3, 2),
        "mfu_bound_serial": base["mfu_bound_serial"],
        "mfu_bound_serial_with_concat": with_concat,
    }


def concat_microbench(name: str = "InceptionV3", batch: int = 128) -> Dict[str, Any]:
    """On-chip concat evidence (B4-style measured per-op bound): slope-
    time an isolated `lax.concatenate` at every unique concat shape of
    the model's forward, sum by occurrence, and fold the MEASURED
    copy wall into the serial conv roofline. If the corrected ceiling
    comes down to the measured MFU, the roofline gap was concat HBM
    traffic and the measured number is the architecture's honest
    ceiling; if not, a fused branch-concat (a Pallas epilogue writing
    branch outputs at channel offsets) still has headroom to claim."""
    import jax.numpy as jnp
    from jax import lax

    from ..benchmarks import device_seconds_per_iter, poke

    concats, tot_flops = _concat_shapes(name, batch)
    base = analyze(name, batch)
    t_parts = 0.0
    concat_bytes = 0.0
    for (ins, out_shape, dim), cnt in concats.items():
        args = [jnp.zeros(s, jnp.bfloat16) for s in ins]
        concat_bytes += cnt * 2.0 * (
            sum(math.prod(s) for s in ins) + math.prod(out_shape)
        )

        def step(i, acc, *ops, dim=dim):
            y = lax.concatenate((poke(ops[0], acc),) + ops[1:], dim)
            return jnp.max(y.astype(jnp.float32))

        t_parts += device_seconds_per_iter(
            step, *args, chains=(6, 24), reps=3
        ) * cnt
    t_serial = base["roofline_ms_serial"] / 1e3
    t_concat_const = concat_bytes / HBM_BW
    eff_bw_meas = concat_bytes / t_parts if t_parts > 0 else None
    return {
        "model": name,
        "batch": batch,
        "concat_sites": sum(concats.values()),
        "concat_unique_shapes": len(concats),
        "concat_gbytes": round(concat_bytes / 1e9, 2),
        "concat_ms_measured": round(t_parts * 1e3, 2),
        "concat_bw_gb_per_s": (
            round(eff_bw_meas / 1e9, 1) if eff_bw_meas else None
        ),
        "mfu_bound_serial": base["mfu_bound_serial"],
        "mfu_bound_serial_with_concat": round(
            tot_flops / PEAK / (t_serial + t_parts), 3
        ),
        # the CPU-safe `concat_analysis` numbers, from the SAME trace
        # (the bench embeds both without paying a second jaxpr trace
        # + roofline pass)
        "concat_ms_at_stream_bw": round(t_concat_const * 1e3, 2),
        "mfu_bound_serial_with_concat_stream_bw": round(
            tot_flops / PEAK / (t_serial + t_concat_const), 3
        ),
        "note": "isolated copies are pessimistic the same way B4's "
                "isolated convs were (XLA can overlap a concat with "
                "MXU work), so the corrected bound brackets the truth "
                "from below while the concat-blind roofline brackets "
                "it from above",
    }


def main() -> None:
    args = [
        a for a in sys.argv[1:]
        if a not in ("--microbench", "--concat", "--concat-microbench")
    ]

    def model_batch(default_model, default_batch=128):
        """(model, batch) from the positional operands — the batch
        arrives as a string and must be cast before it reaches a
        shape tuple."""
        model = args[0] if args else default_model
        batch = int(args[1]) if len(args) > 1 else default_batch
        return model, batch

    if "--microbench" in sys.argv[1:]:
        print(json.dumps(microbench(*model_batch("EfficientNetB4"))))
        return
    if "--concat-microbench" in sys.argv[1:]:
        print(json.dumps(concat_microbench(*model_batch("InceptionV3"))))
        return
    if "--concat" in sys.argv[1:]:
        print(json.dumps(
            concat_analysis(*model_batch("InceptionV3")), indent=1
        ))
        return
    targets = args or ["ResNet50", "InceptionV3", "EfficientNetB4"]
    out = [analyze(t, b) for t in targets for b in (32, 128)]
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
