"""Analytical conv roofline: why each CNN's MFU ceiling sits where it
does on TPU (VERDICT r2 item 3 — "explain the roofline" evidence).

For every `conv_general_dilated` in a model's traced jaxpr, viewed as
a matmul (M = N·Ho·Wo output rows, K = kh·kw·Cin, Nc = Cout):

- **MXU term**: the 128x128 systolic array pads K and Nc to 128
  lanes; tile utilization = (K/K_pad)·(Nc/Nc_pad). Inception's odd
  branch widths (48, 96, 80...) pad badly — its flop-weighted tile
  utilization is ~0.69 vs ResNet50's ~0.89. That alone caps MFU.
- **HBM term**: bytes(input + weights + output at bf16) / stream
  bandwidth. Depthwise convs (feature_group_count = C) never touch
  the MXU — they are pure VPU streams, so their time is entirely this
  term. EfficientNet's depthwise stages carry ~7% of its FLOPs but a
  large share of its wall time.

Per conv, time = max(MXU, HBM) (no overlap assumed within a conv);
summing gives a **pessimistic** serial roofline, while
max(sum MXU, sum HBM) gives an **optimistic** perfectly-pipelined
one. Measured MFU should land between the implied bounds — if it
sits below the pessimistic bound, something is actually wrong (a
layout/algorithm problem), not "the architecture".

Run: ``python -m dml_tpu.tools.conv_roofline [model ...]``
(CPU-safe: only traces jaxprs, compiles nothing).
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict

# measured stream bandwidth on this chip (~650-750 GB/s effective on
# the bench lm decode path, latest BENCH_r* artifact; spec 819)
HBM_BW = 750e9
PEAK = 197e12  # v5e dense bf16


def analyze(name: str, batch: int) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from ..models.params_io import init_variables
    from ..models.registry import get_model

    spec = get_model(name)
    v = init_variables(spec, dtype=jnp.bfloat16)
    model = spec.build(dtype=jnp.bfloat16)
    x = jnp.zeros((batch, *spec.input_size, 3), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(lambda v, x: model.apply(v, x, train=False))(v, x)

    tot_flops = mxu_flops = w_util = 0.0
    t_serial = t_mxu_sum = t_mem_sum = 0.0
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name != "conv_general_dilated":
            continue
        lhs = eqn.invars[0].aval
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        fg = eqn.params.get("feature_group_count", 1)
        kh, kw, cin_g, cout = rhs.shape  # HWIO
        n, ho, wo, _ = out.shape
        flops = 2.0 * n * ho * wo * kh * kw * cin_g * cout
        tot_flops += flops
        bytes_ = 2.0 * (
            math.prod(lhs.shape) + math.prod(rhs.shape) + math.prod(out.shape)
        )
        t_mem = bytes_ / HBM_BW
        t_mem_sum += t_mem
        if fg > 1:  # depthwise: VPU stream, no MXU work
            t_serial += t_mem
            continue
        k_dim, n_dim = kh * kw * cin_g, cout
        util = (
            (k_dim / (math.ceil(k_dim / 128) * 128))
            * (n_dim / (math.ceil(n_dim / 128) * 128))
        )
        t_mxu = flops / (PEAK * util)
        mxu_flops += flops
        w_util += flops * util
        t_mxu_sum += t_mxu
        t_serial += max(t_mxu, t_mem)

    t_pipelined = max(t_mxu_sum, t_mem_sum)
    return {
        "model": name,
        "batch": batch,
        "conv_gflops": round(tot_flops / 1e9, 1),
        "mxu_flop_share": round(mxu_flops / tot_flops, 3),
        "tile_util_flop_weighted": round(w_util / max(mxu_flops, 1), 3),
        "mfu_bound_serial": round(tot_flops / PEAK / t_serial, 3),
        "mfu_bound_pipelined": round(tot_flops / PEAK / t_pipelined, 3),
        "roofline_ms_serial": round(t_serial * 1e3, 2),
        "roofline_ms_pipelined": round(t_pipelined * 1e3, 2),
    }


def main() -> None:
    targets = sys.argv[1:] or ["ResNet50", "InceptionV3", "EfficientNetB4"]
    out = [analyze(t, b) for t in targets for b in (32, 128)]
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
