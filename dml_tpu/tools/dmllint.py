"""dmllint — project-native async-hazard & cross-artifact drift linter.

Every robustness PR in this repo's history hand-fixed the same
recurring hazard classes in the asyncio control plane: fire-and-forget
tasks that wedge teardown (the PR-3 ``wait_for`` wedge), blanket
``except Exception: pass`` that eats real bugs, wire-message handlers
drifting from the ``MsgType`` enum, and hand-mirrored lists (pytest
markers, claim_check summary keys, the observability docstring map)
silently desynchronizing. This module catches those classes
mechanically at test time — ``tests/test_dmllint.py`` enforces ZERO
un-baselined findings in tier-1 — instead of re-discovering them one
chaos soak at a time.

Run it::

    python -m dml_tpu.tools.dmllint [--json] [--root DIR] [--baseline F]
                                    [--rules R1,R2] [--paths GLOB,GLOB]
    python -m dml_tpu lint            # same, as a CLI verb

``--rules``/``--paths`` narrow what is REPORTED (iterate on one rule
or one file without the full-repo noise); the whole tree is always
scanned and stale-baseline reporting pauses while filtering. ``--json``
output carries a ``schema_version`` field.

Exit codes (CI contract): 0 = clean, 1 = un-baselined findings,
2 = internal error (unparseable source, malformed baseline).

Rule catalog
------------

Async-hazard rules (pure AST, per file, over ``dml_tpu/`` + ``tests/``
+ ``bench.py``):

- ``naked-task`` — ``asyncio.create_task(...)`` / ``ensure_future``
  as a bare expression statement: the handle is neither stored, reaped
  via ``cluster.util.reap_task``, nor awaited, so teardown can never
  cancel-and-join it and its exception is silently dropped (the exact
  class behind the PR-3 dispatch wedge).
- ``silent-except`` — a bare ``except:``, ``except Exception`` or
  ``except BaseException`` (alone or in a tuple) whose body is ONLY
  ``pass``: real bugs die invisibly. Narrow the type, or log what was
  swallowed; pass-only bodies on NARROW types are fine.
- ``blocking-async`` — a known blocking call (``time.sleep``, sync
  ``subprocess.run/call/check_call/check_output/Popen``,
  ``socket.create_connection/getaddrinfo/gethostbyname``,
  ``os.system``) lexically inside ``async def``: it stalls the whole
  event loop. Plain ``open()`` on small local files is deliberately
  NOT flagged (the store's atomic-write path uses it by design).
- ``unseeded-seam`` — module-global ``random.*`` (anything except the
  seeded ``random.Random``/``SystemRandom`` constructors, including
  ``from random import <fn>``) or wall-clock ``time.time()`` /
  ``time.time_ns()`` inside the determinism seams
  (``cluster/chaos.py``, ``ingress/loadgen.py``): same seed must mean
  identical schedule, and the injected-clock/seeded-rng discipline is
  what the chaos replay + loadgen trace guarantees rest on.

Cross-artifact drift rules (static introspection of the named
artifacts; each rule is skipped when its artifact files are absent,
so fixture trees exercise them selectively):

- ``drift-wire-handlers`` — ``cluster/wire.py``'s ``HANDLER_OWNERS``
  registry vs reality: every ``MsgType`` member must have exactly one
  declared owner; a class-owned type must actually be registered (via
  ``.register(MsgType.X, self._h_y)``) by that class and no other; a
  ``rid-fallback`` type must NOT be registered anywhere; an
  ``IntroducerService`` type must be referenced by the introducer's
  inline dispatch; every member must be referenced somewhere outside
  wire.py (dead protocol members accrete silently); handler callables
  must follow the ``_h_*`` naming contract; and no code may reference
  an undeclared ``MsgType.X``.
- ``drift-metrics-map`` — the machine-readable "Metric map" section
  of ``observability.py``'s module docstring vs every
  ``*.counter/gauge/histogram("name", ...)`` registration in
  ``dml_tpu/``: both directions must match exactly.
- ``drift-summary-keys`` — ``tools/claim_check.py``'s summary-only
  gates read keys off the bench compact line; every key a gate reads
  must exist in ``bench.py``'s summary dict AND survive the
  last-resort compact-line trim (``_COMPACT_KEEP_KEYS``), and every
  ``_COMPACT_DROP_ORDER`` / keep entry must be a real summary key —
  a typo'd key silently never gates / never trims.
- ``drift-pytest-markers`` — markers used in ``tests/`` must be
  registered in ``pytest.ini``; the ``pytest.ini`` registry and the
  ``tests/conftest.py`` mirror must be identical sets; a registered
  marker no test uses is flagged (the mirror only stays honest while
  every entry is load-bearing).
- ``drift-span-names`` — every literal ``start_span("<name>", ...)``
  call site in the tree must use a name declared in
  ``dml_tpu/tracing.py``'s ``SPAN_NAMES`` registry (the stage
  vocabulary the tail-attribution table reports); a registered name no
  call site emits is flagged, and a NON-literal span name in
  ``dml_tpu/`` (outside tracing.py itself) is flagged as unverifiable
  — stage names in the attribution table must not be able to drift
  from the instrumentation.
- ``drift-alert-names`` — every literal ``fire_alert("<name>", ...)``
  / ``resolve_alert("<name>", ...)`` call site in the tree must use a
  name declared in ``dml_tpu/signal.py``'s ``ALERT_NAMES`` registry
  (the closed alert vocabulary operators page on); a registered name
  no call site emits is flagged, and a NON-literal alert name in
  ``dml_tpu/`` (outside signal.py itself, whose manager/driver
  machinery passes names through variables by design) is flagged as
  unverifiable — the pager catalog must not be able to drift from the
  emission sites.

Flow-aware rules (implemented in the sibling ``dmlflow`` module — see
its docstring for the full semantics and recognized suppressions):

- ``race-yield-hazard`` — per ``async def`` in ``dml_tpu/``, a
  statement-ordered model of ``self.*`` / module-global mutable state:
  flags check-then-act sequences whose branch test and mutation of the
  same attribute straddle an ``await`` (the interleaving window), and
  acquire/release window markers whose release is not on the
  ``try/finally`` cancellation path. Recognized await-safe idioms —
  re-check-after-await, one ``async with <lock>`` across the whole
  window, snapshot-into-local — are not flagged.
- ``drift-wire-payloads`` — infers each ``MsgType``'s payload schema
  from every send site (dict literals and locally-built dicts) vs the
  keys its registered handler / reply-await sites read
  (``msg.data["k"]`` = required, ``.get("k")`` = optional), and
  cross-checks wire.py's docstring "Payload map (lint-enforced)"
  section in both directions: required-read-but-never-sent,
  conditionally-sent-but-required, sent-but-never-read, and any
  map/wire disagreement are findings.

Baseline
--------

``tools/dmllint_baseline.json`` grandfathers accepted findings. Each
entry is ``{"key": <finding key>, "justification": <non-empty why>}``;
an entry without a justification is a malformed baseline (exit 2). A
baselined finding is suppressed; a baseline entry matching NO current
finding is itself reported as ``baseline-stale`` so the file can only
shrink toward empty. Finding keys are scope-anchored
(``rule:path:qualname:ordinal``), not line-anchored, so unrelated
edits above a baselined site don't churn the file.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

# rule ids (the catalog above is the human contract; this is the code's)
R_NAKED = "naked-task"
R_SILENT = "silent-except"
R_BLOCKING = "blocking-async"
R_UNSEEDED = "unseeded-seam"
R_WIRE = "drift-wire-handlers"
R_METRICS = "drift-metrics-map"
R_SUMMARY = "drift-summary-keys"
R_MARKERS = "drift-pytest-markers"
R_SPANS = "drift-span-names"
R_ALERTS = "drift-alert-names"
# flow-aware passes (implemented in the sibling dmlflow module)
R_RACE = "race-yield-hazard"
R_PAYLOAD = "drift-wire-payloads"
R_STALE = "baseline-stale"

ALL_RULES = (
    R_NAKED, R_SILENT, R_BLOCKING, R_UNSEEDED,
    R_WIRE, R_METRICS, R_SUMMARY, R_MARKERS, R_SPANS, R_ALERTS,
    R_RACE, R_PAYLOAD, R_STALE,
)

#: --json output contract version: bumped when the shape of the JSON
#: document changes (2 = schema_version/rules fields + flow rules)
JSON_SCHEMA_VERSION = 2

#: blocking calls flagged inside ``async def`` (module attr, call name)
BLOCKING_CALLS: Set[Tuple[str, str]] = {
    ("time", "sleep"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"), ("socket", "getaddrinfo"),
    ("socket", "gethostbyname"),
    ("os", "system"),
}

#: files where unseeded randomness / wall clocks break determinism
SEAM_FILES = ("dml_tpu/cluster/chaos.py", "dml_tpu/ingress/loadgen.py")

#: seeded constructors allowed through the seam rule
SEEDED_CTORS = {"Random", "SystemRandom"}

#: pytest's built-in marks — usable without registration
BUILTIN_MARKS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
}

DEFAULT_BASELINE = "dml_tpu/tools/dmllint_baseline.json"


class LintInternalError(Exception):
    """Analyzer could not run (unparseable input, malformed baseline).

    Maps to exit code 2 so CI can tell 'tree has findings' from
    'linter is broken'."""


@dataclass(frozen=True, order=True)
class Finding:
    path: str  # repo-relative posix path
    line: int
    rule: str
    msg: str
    key: str  # stable identity for the baseline (scope, not line)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}"


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def scan_paths(root: str) -> List[str]:
    """The lint surface: dml_tpu/ + tests/ + bench.py (deterministic
    order; __pycache__ excluded)."""
    out: List[str] = []
    for sub in ("dml_tpu", "tests"):
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return out


def _parse(path: str, rel: str) -> ast.Module:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), filename=rel)
    except SyntaxError as e:
        raise LintInternalError(f"cannot parse {rel}: {e}") from e


# ----------------------------------------------------------------------
# async-hazard rules (per-file AST)
# ----------------------------------------------------------------------


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = e.attr if isinstance(e, ast.Attribute) else getattr(e, "id", None)
        if name in ("Exception", "BaseException"):
            return True
    return False


class _HazardVisitor(ast.NodeVisitor):
    """One pass per file for all four async-hazard rules, tracking the
    enclosing scope qualname (finding keys anchor to scope+ordinal so
    baselines survive line drift)."""

    def __init__(self, rel: str) -> None:
        self.rel = rel
        self.scope: List[str] = []
        self.async_depth = 0
        self.seam = rel in SEAM_FILES
        self.raw: List[Tuple[str, str, int, str]] = []  # rule, scope, line, msg

    def _emit(self, rule: str, line: int, msg: str) -> None:
        self.raw.append((rule, ".".join(self.scope) or "<module>", line, msg))

    # -- scope / async-context tracking --------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a SYNC def nested in an async def runs outside the loop
        # thread (executor / to_thread) — blocking calls there are fine
        self.scope.append(node.name)
        saved, self.async_depth = self.async_depth, 0
        self.generic_visit(node)
        self.async_depth = saved
        self.scope.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.scope.append(node.name)
        self.async_depth += 1
        self.generic_visit(node)
        self.async_depth -= 1
        self.scope.pop()

    # -- naked-task -----------------------------------------------------
    def visit_Expr(self, node: ast.Expr) -> None:
        v = node.value
        if isinstance(v, ast.Call):
            name = _call_name(v.func)
            if name in ("create_task", "ensure_future"):
                self._emit(
                    R_NAKED, node.lineno,
                    f"{name}(...) handle discarded — store it, reap it "
                    "via cluster.util.reap_task at teardown, or await "
                    "it (a dropped task can neither be cancelled nor "
                    "report its exception)",
                )
        self.generic_visit(node)

    # -- silent-except --------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _is_broad_handler(node) and all(
            isinstance(s, ast.Pass) for s in node.body
        ):
            what = "bare except" if node.type is None else "except Exception"
            self._emit(
                R_SILENT, node.lineno,
                f"{what} with a pass-only body swallows real bugs — "
                "narrow the exception type or log what was caught",
            )
        self.generic_visit(node)

    # -- blocking-async + unseeded-seam ---------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            mod, attr = f.value.id, f.attr
            if self.async_depth and (mod, attr) in BLOCKING_CALLS:
                self._emit(
                    R_BLOCKING, node.lineno,
                    f"blocking {mod}.{attr}(...) inside async def stalls "
                    "the event loop — await the async form or push it "
                    "through asyncio.to_thread",
                )
            if self.seam:
                if mod == "random" and attr not in SEEDED_CTORS:
                    self._emit(
                        R_UNSEEDED, node.lineno,
                        f"module-global random.{attr}(...) in a "
                        "determinism seam — use a seeded "
                        "random.Random(seed) instance (same seed must "
                        "mean identical schedule)",
                    )
                if mod == "time" and attr in ("time", "time_ns"):
                    self._emit(
                        R_UNSEEDED, node.lineno,
                        f"wall-clock time.{attr}() in a determinism "
                        "seam — use the injected clock / loop.time()",
                    )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if self.seam and node.module == "random":
            bad = [a.name for a in node.names if a.name not in SEEDED_CTORS]
            if bad:
                self._emit(
                    R_UNSEEDED, node.lineno,
                    f"from random import {', '.join(bad)} in a "
                    "determinism seam enables unseeded module-global "
                    "randomness — import random.Random and seed it",
                )
        self.generic_visit(node)


def analyze_source(src: str, rel: str) -> List[Finding]:
    """Run the four async-hazard rules over one file's source."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        raise LintInternalError(f"cannot parse {rel}: {e}") from e
    return analyze_tree(tree, rel)


def analyze_tree(tree: ast.Module, rel: str) -> List[Finding]:
    v = _HazardVisitor(rel)
    v.visit(tree)
    # scope-anchored ordinals: n-th finding of this rule in this scope
    counts: Dict[Tuple[str, str], int] = {}
    out: List[Finding] = []
    for rule, scope, line, msg in v.raw:
        n = counts.get((rule, scope), 0)
        counts[(rule, scope)] = n + 1
        out.append(Finding(
            path=rel, line=line, rule=rule, msg=msg,
            key=f"{rule}:{rel}:{scope}:{n}",
        ))
    return out


# ----------------------------------------------------------------------
# drift-wire-handlers
# ----------------------------------------------------------------------


def extract_msgtype_members(wire_tree: ast.Module) -> Dict[str, int]:
    """MsgType member -> enum line, statically (no import)."""
    for node in wire_tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "MsgType":
            out = {}
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.targets[0], ast.Name
                ):
                    out[stmt.targets[0].id] = stmt.lineno
            return out
    return {}


def extract_handler_owners(wire_tree: ast.Module) -> Dict[str, str]:
    """HANDLER_OWNERS dict literal -> {member name: owner string}."""
    for node in wire_tree.body:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "HANDLER_OWNERS"
            for t in targets
        ):
            continue
        val = node.value
        if isinstance(val, ast.Dict):
            out: Dict[str, str] = {}
            for k, v in zip(val.keys, val.values):
                if not (isinstance(k, ast.Attribute)
                        and isinstance(k.value, ast.Name)
                        and k.value.id == "MsgType"):
                    continue
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out[k.attr] = v.value
                elif isinstance(v, ast.Name) and v.id == "RID_FALLBACK":
                    out[k.attr] = "rid-fallback"
            return out
    return {}


def extract_registrations(
    tree: ast.Module, rel: str
) -> List[Tuple[str, str, str, int]]:
    """(member, enclosing class, handler name, line) for every
    ``<x>.register(MsgType.MEMBER, <handler>)`` call."""
    out: List[Tuple[str, str, str, int]] = []

    def walk(node: ast.AST, cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            ncls = child.name if isinstance(child, ast.ClassDef) else cls
            if isinstance(child, ast.Call) and isinstance(
                child.func, ast.Attribute
            ) and child.func.attr == "register" and len(child.args) >= 2:
                a0 = child.args[0]
                if isinstance(a0, ast.Attribute) and isinstance(
                    a0.value, ast.Name
                ) and a0.value.id == "MsgType":
                    h = child.args[1]
                    hname = h.attr if isinstance(h, ast.Attribute) else (
                        h.id if isinstance(h, ast.Name) else "<expr>"
                    )
                    out.append((a0.attr, cls, hname, child.lineno))
            walk(child, ncls)

    walk(tree, "<module>")
    return out


def extract_msgtype_refs(tree: ast.Module) -> Dict[str, int]:
    """member name -> first reference line for ``MsgType.X`` attributes."""
    refs: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "MsgType":
            refs.setdefault(node.attr, node.lineno)
    return refs


def check_wire(
    members: Dict[str, int],
    owners: Dict[str, str],
    registrations: Dict[str, List[Tuple[str, str, str, int]]],
    refs_by_file: Dict[str, Dict[str, int]],
    wire_rel: str,
    introducer_rel: str,
) -> List[Finding]:
    """Pure drift check over statically-extracted wire data.

    ``registrations``: rel -> [(member, class, handler, line)].
    ``refs_by_file``: rel -> {member: line} (wire.py itself included;
    excluded from the dead-member check since HANDLER_OWNERS
    references every member by construction)."""
    fs: List[Finding] = []

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_WIRE, msg=msg,
                          key=f"{R_WIRE}:{subject}"))

    for m, line in members.items():
        if m not in owners:
            f(wire_rel, line, f"unowned:{m}",
              f"MsgType.{m} has no HANDLER_OWNERS entry — declare its "
              "owning service (or rid-fallback)")
    for m in owners:
        if m not in members:
            f(wire_rel, 1, f"ghost-owner:{m}",
              f"HANDLER_OWNERS claims MsgType.{m} which is not a "
              "declared enum member")

    regs_by_member: Dict[str, List[Tuple[str, str, str, int]]] = {}
    for rel, regs in registrations.items():
        for member, cls, handler, line in regs:
            regs_by_member.setdefault(member, []).append(
                (rel, cls, handler, line))
            if member not in members:
                f(rel, line, f"undeclared:{member}:{rel}",
                  f"handler registered for undeclared MsgType.{member}")
            if not (handler.startswith("_h_") or handler == "<expr>"):
                f(rel, line, f"handler-name:{member}:{handler}",
                  f"handler {handler!r} for MsgType.{member} breaks the "
                  "_h_* naming contract")

    intro_refs = refs_by_file.get(introducer_rel, {})
    for m, owner in owners.items():
        if m not in members:
            continue
        regs = regs_by_member.get(m, [])
        if owner == "rid-fallback":
            for rel, cls, handler, line in regs:
                f(rel, line, f"fallback-registered:{m}:{cls}",
                  f"MsgType.{m} is declared rid-fallback but {cls} "
                  f"registers {handler} for it — own it in "
                  "HANDLER_OWNERS or drop the registration")
        elif owner == "IntroducerService":
            if m not in intro_refs:
                f(wire_rel, members[m], f"intro-unhandled:{m}",
                  f"MsgType.{m} is declared IntroducerService-owned "
                  "but the introducer's dispatch never references it")
        else:
            classes = {cls for _, cls, _, _ in regs}
            if owner not in classes:
                f(wire_rel, members[m], f"unregistered:{m}",
                  f"MsgType.{m} is owned by {owner} but {owner} never "
                  "registers a handler for it")
            for rel, cls, handler, line in regs:
                if cls != owner:
                    f(rel, line, f"wrong-owner:{m}:{cls}",
                      f"MsgType.{m} is owned by {owner} but {cls} "
                      f"registers {handler} for it")

    for m, line in members.items():
        used = any(
            m in refs for rel, refs in refs_by_file.items() if rel != wire_rel
        )
        if not used:
            f(wire_rel, line, f"dead-member:{m}",
              f"MsgType.{m} is referenced nowhere outside wire.py — "
              "dead protocol surface (remove it; reserve the value in "
              "a comment)")
    return fs


def rule_wire(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    wire_rel = "dml_tpu/cluster/wire.py"
    if wire_rel not in trees:
        return []
    wire_tree = trees[wire_rel]
    members = extract_msgtype_members(wire_tree)
    owners = extract_handler_owners(wire_tree)
    if not members:
        return []
    # registrations only from product code: tests wire ad-hoc fakes
    registrations = {
        rel: extract_registrations(t, rel)
        for rel, t in trees.items() if rel.startswith("dml_tpu/")
    }
    refs_by_file = {rel: extract_msgtype_refs(t) for rel, t in trees.items()}
    return check_wire(
        members, owners, registrations, refs_by_file,
        wire_rel, "dml_tpu/cluster/introducer.py",
    )


# ----------------------------------------------------------------------
# drift-metrics-map
# ----------------------------------------------------------------------

_METRIC_MAP_HEADER = "Metric map (lint-enforced)"
_METRIC_LINE_RE = re.compile(r"^ {4}([a-z][a-z0-9_]*)(?=\s|$)")


def parse_metric_map(docstring: str) -> Optional[Set[str]]:
    """The machine-readable metric list from observability.py's module
    docstring: lines indented 4 spaces, ``name  description``, in the
    section opened by the header line. None = no map section at all."""
    lines = docstring.splitlines()
    try:
        start = next(
            i for i, ln in enumerate(lines)
            if ln.strip() == _METRIC_MAP_HEADER
        )
    except StopIteration:
        return None
    names: Set[str] = set()
    in_list = False
    for ln in lines[start + 1:]:
        m = _METRIC_LINE_RE.match(ln)
        if m:
            in_list = True
            names.add(m.group(1))
        elif in_list and ln.strip() and not ln.startswith(" "):
            break  # next unindented section
    return names


def collect_metric_registrations(
    trees: Dict[str, ast.Module]
) -> Dict[str, Tuple[str, int]]:
    """metric name -> (rel, line) for every counter/gauge/histogram
    registration with a literal name, product code only."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel in sorted(trees):
        if not rel.startswith("dml_tpu/"):
            continue
        for node in ast.walk(trees[rel]):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                if re.fullmatch(r"[a-z][a-z0-9_]*", name):
                    out.setdefault(name, (rel, node.lineno))
    return out


def check_metrics(
    map_names: Optional[Set[str]],
    code_names: Dict[str, Tuple[str, int]],
    obs_rel: str,
) -> List[Finding]:
    fs: List[Finding] = []
    if map_names is None:
        fs.append(Finding(
            path=obs_rel, line=1, rule=R_METRICS,
            msg=f"module docstring has no '{_METRIC_MAP_HEADER}' "
                "section — the metric map is the operator's index and "
                "is lint-enforced",
            key=f"{R_METRICS}:no-map",
        ))
        return fs
    for name in sorted(map_names - set(code_names)):
        fs.append(Finding(
            path=obs_rel, line=1, rule=R_METRICS,
            msg=f"metric {name!r} is in the docstring map but no code "
                "registers it — stale map entry",
            key=f"{R_METRICS}:map-only:{name}",
        ))
    for name in sorted(set(code_names) - map_names):
        rel, line = code_names[name]
        fs.append(Finding(
            path=rel, line=line, rule=R_METRICS,
            msg=f"metric {name!r} is registered here but missing from "
                "observability.py's docstring metric map",
            key=f"{R_METRICS}:code-only:{name}",
        ))
    return fs


def rule_metrics(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    obs_rel = "dml_tpu/observability.py"
    if obs_rel not in trees:
        return []
    doc = ast.get_docstring(trees[obs_rel]) or ""
    return check_metrics(
        parse_metric_map(doc), collect_metric_registrations(trees), obs_rel
    )


# ----------------------------------------------------------------------
# drift-summary-keys
# ----------------------------------------------------------------------


def extract_bench_summary_keys(tree: ast.Module) -> Dict[str, int]:
    """Keys bench.py can emit in its summary: every dict literal
    assigned to a name ``summary`` plus ``summary[<const>] = ...``."""
    keys: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            t0 = node.targets[0]
            if (isinstance(t0, ast.Name) and t0.id == "summary"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.setdefault(k.value, k.lineno)
            if (isinstance(t0, ast.Subscript)
                    and isinstance(t0.value, ast.Name)
                    and t0.value.id == "summary"
                    and isinstance(t0.slice, ast.Constant)
                    and isinstance(t0.slice.value, str)):
                keys.setdefault(t0.slice.value, node.lineno)
    return keys


def _module_const_strs(tree: ast.Module, name: str) -> Optional[Dict[str, int]]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return {
                    e.value: e.lineno
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return None


def extract_claim_gate_keys(tree: ast.Module) -> Dict[str, int]:
    """Summary keys claim_check's summary-only gates read: inside any
    function that binds ``X = <...>.get("summary") ...``, every
    ``X.get("k")`` / ``X["k"]`` constant key."""
    keys: Dict[str, int] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        bound: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Name
            ):
                for sub in ast.walk(node.value):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "get" and sub.args
                            and isinstance(sub.args[0], ast.Constant)
                            and sub.args[0].value == "summary"):
                        bound.add(node.targets[0].id)
        if not bound:
            continue
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in bound and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                keys.setdefault(node.args[0].value, node.lineno)
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in bound
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                keys.setdefault(node.slice.value, node.lineno)
    return keys


def check_summary(
    summary_keys: Dict[str, int],
    keep_keys: Optional[Dict[str, int]],
    drop_keys: Optional[Dict[str, int]],
    gate_keys: Dict[str, int],
    bench_rel: str,
    claim_rel: str,
) -> List[Finding]:
    fs: List[Finding] = []

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_SUMMARY, msg=msg,
                          key=f"{R_SUMMARY}:{subject}"))

    if keep_keys is None:
        f(bench_rel, 1, "no-keep-list",
          "bench.py has no module-level _COMPACT_KEEP_KEYS tuple — the "
          "last-resort compact-line survivors must be declared where "
          "the linter (and claim_check) can see them")
        keep_keys = {}
    for k, line in sorted(gate_keys.items()):
        if k not in summary_keys:
            f(claim_rel, line, f"gate-not-emitted:{k}",
              f"claim_check summary gate reads {k!r} but bench.py "
              "never emits that summary key — the gate can never fire")
        elif keep_keys and k not in keep_keys:
            f(claim_rel, line, f"gate-trimmed:{k}",
              f"claim_check summary gate reads {k!r} but the key does "
              "not survive bench.py's last-resort compact-line trim "
              "(_COMPACT_KEEP_KEYS) — a trimmed driver tail would "
              "silently skip the gate")
    for k, line in sorted((drop_keys or {}).items()):
        if k not in summary_keys:
            f(bench_rel, line, f"drop-unknown:{k}",
              f"_COMPACT_DROP_ORDER entry {k!r} is not a summary key — "
              "a typo here means some other key never gets trimmed")
    for k, line in sorted(keep_keys.items()):
        if k not in summary_keys:
            f(bench_rel, line, f"keep-unknown:{k}",
              f"_COMPACT_KEEP_KEYS entry {k!r} is not a summary key — "
              "the last-resort line would carry a null nobody emits")
    return fs


def rule_summary(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    bench_rel, claim_rel = "bench.py", "dml_tpu/tools/claim_check.py"
    if bench_rel not in trees or claim_rel not in trees:
        return []
    bench_tree = trees[bench_rel]
    return check_summary(
        extract_bench_summary_keys(bench_tree),
        _module_const_strs(bench_tree, "_COMPACT_KEEP_KEYS"),
        _module_const_strs(bench_tree, "_COMPACT_DROP_ORDER"),
        extract_claim_gate_keys(trees[claim_rel]),
        bench_rel, claim_rel,
    )


# ----------------------------------------------------------------------
# drift-span-names
# ----------------------------------------------------------------------

TRACING_REL = "dml_tpu/tracing.py"


def collect_span_call_sites(
    trees: Dict[str, ast.Module],
) -> Tuple[Dict[str, List[Tuple[str, int]]], List[Tuple[str, int]]]:
    """-> (span name -> [(path, line), ...] for every LITERAL
    ``start_span("<name>", ...)`` call, [(path, line), ...] of
    non-literal call sites). tracing.py itself is excluded — its
    generic machinery passes names through variables by design."""
    literal: Dict[str, List[Tuple[str, int]]] = {}
    dynamic: List[Tuple[str, int]] = []
    for rel, tree in sorted(trees.items()):
        if rel == TRACING_REL:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func) != "start_span":
                continue
            name_arg: Optional[ast.AST] = (
                node.args[0] if node.args else None
            )
            if name_arg is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name_arg = kw.value
            if isinstance(name_arg, ast.Constant) and isinstance(
                name_arg.value, str
            ):
                literal.setdefault(name_arg.value, []).append(
                    (rel, node.lineno)
                )
            else:
                dynamic.append((rel, node.lineno))
    return literal, dynamic


def collect_tracing_literals(tree: ast.Module) -> Set[str]:
    """Span names the tracer's OWN machinery emits, counting as used
    without a start_span call site. Deliberately narrow — only (a)
    module-level ``NAME = "str"`` aliases (``SPAN_ROOT``) and (b)
    string literals passed positionally to a ``Span(...)``
    construction (``note_exemplar``'s marker). Any broader net (e.g.
    every string constant in the module) would let incidental
    literals — the attribution code's stage sets, docstring fragments
    — permanently mask the registered-but-never-emitted check."""
    out: Set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out.add(node.value.value)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and _call_name(node.func) == "Span"):
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    out.add(arg.value)
    return out


def check_span_names(
    registry: Optional[Dict[str, int]],
    literal: Dict[str, List[Tuple[str, int]]],
    dynamic: List[Tuple[str, int]],
    tracing_literals: Set[str],
    tracing_rel: str,
) -> List[Finding]:
    fs: List[Finding] = []

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_SPANS, msg=msg,
                          key=f"{R_SPANS}:{subject}"))

    if registry is None:
        f(tracing_rel, 1, "no-registry",
          "tracing.py has no module-level SPAN_NAMES tuple — the span "
          "vocabulary must be declared where the linter (and the "
          "attribution table) can see it")
        return fs
    for name, sites in sorted(literal.items()):
        if name not in registry:
            path, line = sites[0]
            f(path, line, f"unregistered:{name}",
              f"start_span({name!r}) uses a span name not declared in "
              "tracing.SPAN_NAMES — add it to the registry first, or "
              "the attribution table silently drops this stage")
    for name, line in sorted(registry.items()):
        if name not in literal and name not in tracing_literals:
            f(tracing_rel, line, f"unused:{name}",
              f"SPAN_NAMES entry {name!r} has no start_span call site "
              "— a stage the table reports but nothing ever emits")
    for path, line in dynamic:
        if path.startswith("dml_tpu/"):
            f(path, line, f"dynamic:{path}:{line}",
              "start_span with a non-literal name cannot be checked "
              "against SPAN_NAMES — pass the registry constant "
              "directly so the stage vocabulary stays closed")
    return fs


def rule_spans(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    if TRACING_REL not in trees:
        return []
    tracing_tree = trees[TRACING_REL]
    literal, dynamic = collect_span_call_sites(trees)
    return check_span_names(
        _module_const_strs(tracing_tree, "SPAN_NAMES"),
        literal, dynamic,
        collect_tracing_literals(tracing_tree),
        TRACING_REL,
    )


# ----------------------------------------------------------------------
# drift-alert-names
# ----------------------------------------------------------------------

SIGNAL_REL = "dml_tpu/signal.py"

_ALERT_CALLS = ("fire_alert", "resolve_alert")


def collect_alert_call_sites(
    trees: Dict[str, ast.Module],
) -> Tuple[Dict[str, List[Tuple[str, int]]], List[Tuple[str, int]]]:
    """-> (alert name -> [(path, line), ...] for every LITERAL
    ``fire_alert("<name>", ...)`` / ``resolve_alert("<name>", ...)``
    call, [(path, line), ...] of non-literal call sites). Unlike the
    span rule, signal.py itself is NOT excluded from literal
    collection — its SignalPlane monitors are the primary emitters —
    but its dynamic sites (the ``_drive`` dispatcher, the manager
    pass-throughs) are the machinery's own and are filtered in
    ``check_alert_names``."""
    literal: Dict[str, List[Tuple[str, int]]] = {}
    dynamic: List[Tuple[str, int]] = []
    for rel, tree in sorted(trees.items()):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func) not in _ALERT_CALLS:
                continue
            name_arg: Optional[ast.AST] = (
                node.args[0] if node.args else None
            )
            if name_arg is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name_arg = kw.value
            if isinstance(name_arg, ast.Constant) and isinstance(
                name_arg.value, str
            ):
                literal.setdefault(name_arg.value, []).append(
                    (rel, node.lineno)
                )
            else:
                dynamic.append((rel, node.lineno))
    return literal, dynamic


def check_alert_names(
    registry: Optional[Dict[str, int]],
    literal: Dict[str, List[Tuple[str, int]]],
    dynamic: List[Tuple[str, int]],
    signal_rel: str,
) -> List[Finding]:
    fs: List[Finding] = []

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_ALERTS, msg=msg,
                          key=f"{R_ALERTS}:{subject}"))

    if registry is None:
        f(signal_rel, 1, "no-registry",
          "signal.py has no module-level ALERT_NAMES tuple — the alert "
          "vocabulary must be declared where the linter (and the "
          "on-call runbook) can see it")
        return fs
    for name, sites in sorted(literal.items()):
        if name not in registry:
            path, line = sites[0]
            f(path, line, f"unregistered:{name}",
              f"fire_alert/resolve_alert({name!r}) uses an alert name "
              "not declared in signal.ALERT_NAMES — add it to the "
              "registry first, or the pager catalog silently gains an "
              "undocumented page")
    for name, line in sorted(registry.items()):
        if name not in literal:
            f(signal_rel, line, f"unused:{name}",
              f"ALERT_NAMES entry {name!r} has no fire_alert/"
              "resolve_alert call site — an alert the catalog promises "
              "but nothing ever emits")
    for path, line in dynamic:
        if path.startswith("dml_tpu/") and path != signal_rel:
            f(path, line, f"dynamic:{path}:{line}",
              "fire_alert/resolve_alert with a non-literal name cannot "
              "be checked against ALERT_NAMES — pass the registry "
              "constant directly so the alert vocabulary stays closed")
    return fs


def rule_alerts(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    if SIGNAL_REL not in trees:
        return []
    literal, dynamic = collect_alert_call_sites(trees)
    return check_alert_names(
        _module_const_strs(trees[SIGNAL_REL], "ALERT_NAMES"),
        literal, dynamic, SIGNAL_REL,
    )


# ----------------------------------------------------------------------
# drift-pytest-markers
# ----------------------------------------------------------------------

_INI_MARKER_RE = re.compile(r"^(\s+)([A-Za-z_]\w*)\s*:")


def parse_ini_markers(text: str) -> Optional[Dict[str, int]]:
    """Marker names from pytest.ini's ``markers =`` block. Definition
    lines share the block's minimal indentation; deeper-indented lines
    are description continuations."""
    lines = text.splitlines()
    try:
        start = next(
            i for i, ln in enumerate(lines)
            if re.match(r"^markers\s*=", ln)
        )
    except StopIteration:
        return None
    out: Dict[str, int] = {}
    indent: Optional[int] = None
    for i in range(start + 1, len(lines)):
        ln = lines[i]
        if not ln.strip():
            continue
        if not ln[0].isspace():
            break  # next key or section
        m = _INI_MARKER_RE.match(ln)
        if m:
            if indent is None:
                indent = len(m.group(1))
            if len(m.group(1)) == indent:
                out[m.group(2)] = i + 1
    return out


def parse_conftest_markers(tree: ast.Module) -> Dict[str, int]:
    """Marker names from ``config.addinivalue_line("markers", "<name>:
    ...")`` calls in tests/conftest.py."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "addinivalue_line"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "markers"
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            name = node.args[1].value.split(":", 1)[0].strip()
            if name:
                out[name] = node.lineno
    return out


def collect_used_marks(
    trees: Dict[str, ast.Module]
) -> Dict[str, Tuple[str, int]]:
    """marker -> (rel, line) for every ``pytest.mark.<name>`` in tests/."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel in sorted(trees):
        if not rel.startswith("tests/"):
            continue
        for node in ast.walk(trees[rel]):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr == "mark"
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "pytest"):
                out.setdefault(node.attr, (rel, node.lineno))
    return out


def check_markers(
    ini: Optional[Dict[str, int]],
    conftest: Dict[str, int],
    used: Dict[str, Tuple[str, int]],
    ini_rel: str,
    conftest_rel: str,
) -> List[Finding]:
    fs: List[Finding] = []

    def f(path: str, line: int, subject: str, msg: str) -> None:
        fs.append(Finding(path=path, line=line, rule=R_MARKERS, msg=msg,
                          key=f"{R_MARKERS}:{subject}"))

    if ini is None:
        f(ini_rel, 1, "no-registry",
          "pytest.ini has no `markers =` block — the marker registry "
          "is the canonical config and is lint-enforced")
        return fs
    custom_used = {
        m: loc for m, loc in used.items() if m not in BUILTIN_MARKS
    }
    for m, (rel, line) in sorted(custom_used.items()):
        if m not in ini:
            f(rel, line, f"unregistered:{m}",
              f"pytest marker {m!r} used here is not registered in "
              "pytest.ini — `-m` selections silently miss it and "
              "--strict-markers would fail")
    for m, line in sorted(ini.items()):
        if m not in conftest:
            f(ini_rel, line, f"ini-only:{m}",
              f"marker {m!r} is in pytest.ini but missing from the "
              "tests/conftest.py mirror (direct-module runs would "
              "warn)")
        if m not in custom_used:
            f(ini_rel, line, f"unused:{m}",
              f"registered marker {m!r} is used by no test — drop it "
              "or mark the coverage it was registered for")
    for m, line in sorted(conftest.items()):
        if m not in ini:
            f(conftest_rel, line, f"conftest-only:{m}",
              f"marker {m!r} is in the conftest mirror but not in "
              "pytest.ini (the canonical registry)")
    return fs


def rule_markers(root: str, trees: Dict[str, ast.Module]) -> List[Finding]:
    ini_path = os.path.join(root, "pytest.ini")
    conftest_rel = "tests/conftest.py"
    if not os.path.exists(ini_path) or conftest_rel not in trees:
        return []
    with open(ini_path, encoding="utf-8") as fh:
        ini_text = fh.read()
    return check_markers(
        parse_ini_markers(ini_text),
        parse_conftest_markers(trees[conftest_rel]),
        collect_used_marks(trees),
        "pytest.ini", conftest_rel,
    )


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, str]:
    """key -> justification. Malformed entries are an internal error
    (exit 2): a baseline that can't be trusted must not suppress."""
    if not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise LintInternalError(f"baseline {path}: {e}") from e
    entries = doc.get("entries") if isinstance(doc, dict) else None
    if not isinstance(entries, list):
        raise LintInternalError(
            f"baseline {path}: expected {{'entries': [...]}}"
        )
    out: Dict[str, str] = {}
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not isinstance(e.get("key"), str):
            raise LintInternalError(
                f"baseline {path}: entry {i} has no string 'key'"
            )
        just = e.get("justification")
        if not isinstance(just, str) or not just.strip():
            raise LintInternalError(
                f"baseline {path}: entry {e['key']!r} has no "
                "justification — every grandfathered finding must say "
                "why it is accepted"
            )
        if e["key"] in out:
            raise LintInternalError(
                f"baseline {path}: duplicate key {e['key']!r}"
            )
        out[e["key"]] = just.strip()
    return out


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, str], baseline_rel: str
) -> Tuple[List[Finding], List[Finding]]:
    """-> (un-baselined findings + stale-entry findings, suppressed)."""
    keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    suppressed = [f for f in findings if f.key in baseline]
    for k in sorted(baseline):
        if k not in keys:
            new.append(Finding(
                path=baseline_rel, line=1, rule=R_STALE,
                msg=f"baseline entry {k!r} matches no current finding — "
                    "the hazard is gone; delete the entry (the baseline "
                    "only ever shrinks)",
                key=f"{R_STALE}:{k}",
            ))
    return new, suppressed


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]  # un-baselined (includes baseline-stale)
    suppressed: List[Finding]
    baseline_size: int

    @property
    def clean(self) -> bool:
        return not self.findings


def run_lint(
    root: Optional[str] = None,
    baseline_path: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    paths: Optional[Sequence[str]] = None,
) -> LintResult:
    """Run the analyzer. ``rules``/``paths`` narrow what is REPORTED
    (for iterating on one rule or one file): the whole tree is always
    scanned — cross-artifact rules need the full view — and findings
    are filtered afterwards. While either filter is active,
    baseline-stale reporting is disabled (a partial view cannot judge
    staleness) and the baseline acts as suppression only."""
    from . import dmlflow  # sibling module; imported late (it imports us)

    root = os.path.abspath(root or repo_root())
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    if rules:
        unknown = sorted(set(rules) - set(ALL_RULES))
        if unknown:
            raise LintInternalError(
                f"unknown rule(s) {', '.join(unknown)} — valid: "
                + ", ".join(ALL_RULES)
            )
    trees: Dict[str, ast.Module] = {}
    findings: List[Finding] = []
    for path in scan_paths(root):
        rel = _rel(root, path)
        trees[rel] = _parse(path, rel)  # raises LintInternalError
        findings.extend(analyze_tree(trees[rel], rel))
    for rule_fn in (rule_wire, rule_metrics, rule_summary, rule_markers,
                    rule_spans, rule_alerts,
                    dmlflow.rule_race, dmlflow.rule_payloads):
        findings.extend(rule_fn(root, trees))
    filtered = bool(rules) or bool(paths)
    if rules:
        findings = [f for f in findings if f.rule in set(rules)]
    if paths:
        import fnmatch

        findings = [
            f for f in findings
            if any(fnmatch.fnmatch(f.path, p) for p in paths)
        ]
    baseline = load_baseline(baseline_path)
    new, suppressed = apply_baseline(
        findings, baseline, _rel(root, baseline_path)
    )
    if filtered:
        new = [f for f in new if f.rule != R_STALE]
    # explicit sort key, not dataclass ordering: under `python -m
    # dml_tpu.tools.dmllint` this module is __main__ while dmlflow
    # imports the package copy, so findings from the two passes are
    # instances of two (identical) Finding classes
    sort_key = lambda f: (f.path, f.line, f.rule, f.msg, f.key)  # noqa: E731
    new.sort(key=sort_key)
    suppressed.sort(key=sort_key)
    return LintResult(
        findings=new, suppressed=suppressed, baseline_size=len(baseline)
    )


def bench_block(root: Optional[str] = None) -> Dict[str, Any]:
    """The ``lint`` block bench.py embeds in artifacts (claim_check
    validates it from round 11): the verdict, the un-baselined finding
    count, and the baseline size. Never raises — a broken linter must
    not kill a bench run (the error lands in the block instead)."""
    try:
        res = run_lint(root)

        def n(rule: str) -> int:
            return sum(
                1 for f in res.findings + res.suppressed if f.rule == rule
            )

        return {
            "lint_clean": res.clean,
            "findings": len(res.findings),
            "baseline_size": res.baseline_size,
            # flow-aware pass counts (round-16 gate): findings INCLUDING
            # baselined ones, so the artifact records how many flagged
            # sites exist even on a clean tree
            "race_findings": n(R_RACE),
            "payload_findings": n(R_PAYLOAD),
            "rules": list(ALL_RULES),
        }
    except Exception as e:  # defensive: bench preamble must survive
        return {"lint_clean": False, "error": repr(e)}


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dmllint",
        description="project-native async-hazard & protocol-drift "
                    "linter (see module docstring for the rule catalog)",
    )
    p.add_argument("--root", default=None,
                   help="tree to lint (default: this repo)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "under the root)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="only report these rules (comma-separated; "
                        "stale-baseline reporting is disabled while "
                        "filtering)")
    p.add_argument("--paths", default=None, metavar="GLOB[,GLOB]",
                   help="only report findings whose path matches one of "
                        "these globs (the whole tree is still scanned)")
    args = p.parse_args(argv)
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] \
        if args.rules else None
    paths = [g.strip() for g in args.paths.split(",") if g.strip()] \
        if args.paths else None
    try:
        res = run_lint(args.root, args.baseline, rules=rules, paths=paths)
    except LintInternalError as e:
        if args.json:
            print(json.dumps({"internal_error": str(e),
                              "schema_version": JSON_SCHEMA_VERSION}))
        else:
            print(f"dmllint: internal error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({
            "schema_version": JSON_SCHEMA_VERSION,
            "clean": res.clean,
            "findings": [
                {"path": f.path, "line": f.line, "rule": f.rule,
                 "msg": f.msg, "key": f.key}
                for f in res.findings
            ],
            "suppressed": len(res.suppressed),
            "baseline_size": res.baseline_size,
            "rules": list(rules) if rules else list(ALL_RULES),
        }, indent=2))
    else:
        for f in res.findings:
            print(f.render())
        print(
            f"dmllint: {len(res.findings)} finding(s), "
            f"{len(res.suppressed)} baselined, "
            f"baseline size {res.baseline_size}"
        )
    return 1 if res.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
