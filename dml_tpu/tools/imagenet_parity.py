"""End-to-end pretrained-weight parity against the reference's goldens.

The reference serves stock imagenet-pretrained Keras models
(reference models.py:26,51 — `InceptionV3(weights='imagenet')`,
`ResNet50(weights='imagenet')`) and ships two golden job outputs
(reference download/output_1_127.json, output_2_127.json: per-image
top-5 [wnid, label, score] lists over testfiles_more/ JPEGs).

This tool closes the loop on real weights:

1. *Acquire* imagenet weights — from a single-file pre-converted
   ``.npz`` fixture (``params_io.save_npz_fixture``: tree + embedded
   class index — ONE file dropped anywhere in the search set runs the
   whole report), from a stock Keras ``.h5`` in the Keras cache or a
   directory given via ``DML_TPU_KERAS_WEIGHTS_DIR`` (read TF-free
   with h5py), or by letting Keras download when the environment has
   egress. Hermetic sandboxes have none of these; the tool then
   reports ``skipped`` with the reason rather than failing (the bench
   embeds that verbatim).
2. *Convert* them into the Flax trees with
   `models.params_io.from_keras_model` (the converter whose
   architecture-level correctness is already pinned by
   tests/test_keras_parity.py with random weights).
3. *Serve* them through the real product path — `InferenceEngine`
   (jitted bfloat16 batched forward, uint8 ingest, padded shapes) —
   on the goldens' actual JPEGs.
4. *Validate* label-level agreement three ways per model:
   - top-1 / top-5 agreement between our engine and live Keras on the
     same decoded inputs (converter parity with real weights);
   - top-1 / top-5 agreement between our engine and the reference's
     golden outputs (cross-framework, cross-preprocessing parity) —
     each golden file is assigned to the model that agrees with it
     best, since the reference's job ids don't record the model name.

Run: ``python -m dml_tpu.tools.imagenet_parity [--json]``
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

GOLDEN_DIR = "/root/reference/download"
GOLDEN_IMAGE_DIRS = (
    "/root/reference/testfiles_more",
    "/root/reference/testfiles",
)
# imagenet weight files as Keras names them in ~/.keras/models
_KERAS_WEIGHT_FILES = {
    "ResNet50": "resnet50_weights_tf_dim_ordering_tf_kernels.h5",
    "ResNet101": "resnet101_weights_tf_dim_ordering_tf_kernels.h5",
    "ResNet152": "resnet152_weights_tf_dim_ordering_tf_kernels.h5",
    "InceptionV3": "inception_v3_weights_tf_dim_ordering_tf_kernels.h5",
}
_PARITY_MODELS = ("ResNet50", "InceptionV3")


def _keras_cache_dir() -> str:
    return os.path.expanduser(
        os.path.join(os.environ.get("KERAS_HOME", "~/.keras"), "models")
    )


def candidate_weight_paths(model: str, extra_dir: Optional[str] = None) -> List[str]:
    """Every path probed for `model`'s stock .h5 (whether present or
    not — the skip reason names these exactly, VERDICT r2 item 8).
    `extra_dir` is probed FIRST: the store-staged directory
    (`run_parity_from_store`) outranks env/cache sources."""
    fname = _KERAS_WEIGHT_FILES[model]
    candidates = []
    if extra_dir:
        candidates.append(os.path.join(extra_dir, fname))
    env_dir = os.environ.get("DML_TPU_KERAS_WEIGHTS_DIR")
    if env_dir:
        candidates.append(os.path.join(env_dir, fname))
    candidates.append(os.path.join(_keras_cache_dir(), fname))
    return candidates


def weight_sources(model: str, extra_dir: Optional[str] = None) -> List[str]:
    """Candidate .h5 paths for `model`, existing ones only."""
    return [
        p for p in candidate_weight_paths(model, extra_dir)
        if os.path.exists(p)
    ]


def candidate_npz_paths(model: str, extra_dir: Optional[str] = None) -> List[str]:
    """Every path probed for a pre-converted single-file fixture
    (params_io.save_npz_fixture: converted tree + embedded class
    index) — the ONE-file drop-in that runs the report in hermetic
    environments (VERDICT r3 item 9). `extra_dir` (the store-staged
    directory) is probed first."""
    fname = f"dml_tpu_{model}.npz"
    out = []
    if extra_dir:
        out.append(os.path.join(extra_dir, fname))
    env_dir = os.environ.get("DML_TPU_KERAS_WEIGHTS_DIR")
    if env_dir:
        out.append(os.path.join(env_dir, fname))
    out.append(os.path.join(_keras_cache_dir(), fname))
    out.append(os.path.expanduser(f"~/.dml_tpu/{fname}"))
    return out


def npz_sources(model: str, extra_dir: Optional[str] = None) -> List[str]:
    return [
        p for p in candidate_npz_paths(model, extra_dir)
        if os.path.exists(p)
    ]


def _try_build_keras(model: str):
    """Build the pretrained Keras model, or (None, reason).

    Keras prints download progress to *stdout*; the bench's contract
    is ONE JSON line on stdout, so everything here runs with stdout
    redirected to stderr."""
    import contextlib
    import sys

    with contextlib.redirect_stdout(sys.stderr):
        return _try_build_keras_inner(model)


def _try_build_keras_inner(model: str):
    try:
        import tensorflow as tf  # noqa: F401
        from tensorflow import keras
    except Exception as e:  # pragma: no cover - tf is in the image
        return None, f"tensorflow unavailable: {e!r}"
    tf.config.set_visible_devices([], "GPU")
    builder = {
        "ResNet50": keras.applications.ResNet50,
        "InceptionV3": keras.applications.InceptionV3,
    }[model]
    local = weight_sources(model)
    if local:
        try:
            return builder(weights=local[0]), None
        except Exception as e:
            return None, f"local weights {local[0]} unloadable: {e!r}"
    # last resort: let Keras download (works only with egress)
    try:
        return builder(weights="imagenet"), None
    except Exception as e:
        return None, (
            "imagenet weights unobtainable: no DML_TPU_KERAS_WEIGHTS_DIR, "
            f"no keras cache, download failed ({type(e).__name__})"
        )


def candidate_class_index_paths(extra_dir: Optional[str] = None) -> List[str]:
    """Every local path probed for imagenet_class_index.json — the
    same set models/labels.py searches, so a file found here is the
    one the engine's decode_predictions will actually use."""
    out = []
    if extra_dir:
        out.append(os.path.join(extra_dir, "imagenet_class_index.json"))
    env_dir = os.environ.get("DML_TPU_KERAS_WEIGHTS_DIR")
    if env_dir:
        out.append(os.path.join(env_dir, "imagenet_class_index.json"))
    out.append(os.path.join(_keras_cache_dir(), "imagenet_class_index.json"))
    out.append(os.path.expanduser("~/.dml_tpu/imagenet_class_index.json"))
    return out


def _ensure_class_index() -> Optional[str]:
    """Path to imagenet_class_index.json, fetching via Keras as a last
    resort if the environment allows; None when unobtainable."""
    for p in candidate_class_index_paths():
        if os.path.exists(p):
            return p
    try:
        from tensorflow import keras

        return keras.utils.get_file(
            "imagenet_class_index.json",
            "https://storage.googleapis.com/download.tensorflow.org/data/"
            "imagenet_class_index.json",
        )
    except Exception:
        return None


def load_goldens(golden_dir: str = GOLDEN_DIR) -> Dict[str, Dict[str, list]]:
    """{golden_filename: {image: top5 [[wnid, label, score] x5]}}."""
    out: Dict[str, Dict[str, list]] = {}
    if not os.path.isdir(golden_dir):
        return out
    for fn in sorted(os.listdir(golden_dir)):
        if not fn.endswith(".json"):
            continue
        with open(os.path.join(golden_dir, fn)) as f:
            raw = json.load(f)
        # reference shape: {img: [top5]} with one extra list nesting
        out[fn] = {
            img: (rows[0] if len(rows) == 1 else rows)
            for img, rows in raw.items()
        }
    return out


def resolve_image(name: str) -> Optional[str]:
    for d in GOLDEN_IMAGE_DIRS:
        p = os.path.join(d, name)
        if os.path.exists(p):
            return p
    return None


def _top5_wnids(rows: Sequence[Sequence[Any]]) -> List[str]:
    return [r[0] for r in rows[:5]]


def _agreement(
    ours: Dict[str, List[str]], golden: Dict[str, List[str]]
) -> Dict[str, float]:
    """Label agreement between two {image: top5 wnids} maps."""
    common = sorted(set(ours) & set(golden))
    if not common:
        return {"n": 0, "top1": 0.0, "top5_overlap": 0.0}
    top1 = sum(ours[i][0] == golden[i][0] for i in common) / len(common)
    ovl = sum(
        len(set(ours[i]) & set(golden[i])) / 5 for i in common
    ) / len(common)
    return {"n": len(common), "top1": top1, "top5_overlap": ovl}


def run_parity(
    models: Sequence[str] = _PARITY_MODELS,
    golden_dir: str = GOLDEN_DIR,
    dtype: str = "bfloat16",
    weights_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """The full check. Never raises for missing weights — reports
    skipped-with-reason instead, so the bench can always embed it.

    `weights_dir` is an extra directory probed FIRST for fixtures/.h5/
    class index — `run_parity_from_store` stages store-delivered
    weights there, so an operator `put` is all it takes to feed the
    report on a cluster with no local weight files."""
    goldens = load_goldens(golden_dir)
    if not goldens:
        return {
            "skipped": True,
            "reason": f"no golden outputs found under {golden_dir}",
        }
    report: Dict[str, Any] = {"skipped": False, "models": {}, "dtype": dtype}

    import numpy as np
    import jax.numpy as jnp

    from ..inference.engine import InferenceEngine
    from ..models import get_model
    from ..models.params_io import (
        from_keras_h5,
        from_keras_model,
        init_variables,
    )
    from ..models.preprocess import load_images

    engine = InferenceEngine(
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    )

    # every image any golden references (the two reference job outputs
    # cover disjoint 5-image sets from testfiles_more/)
    images = sorted({img for g in goldens.values() for img in g})
    paths = {img: resolve_image(img) for img in images}
    missing = [i for i, p in paths.items() if p is None]
    if missing:
        return {
            "skipped": True,
            "reason": f"golden images not found: {missing[:5]}",
        }

    # acquire weights per model, in preference order: (1) a
    # pre-converted single-file .npz fixture (tree + embedded class
    # index — one file, zero deps); (2) a stock Keras .h5 read
    # DIRECTLY with h5py (no TensorFlow anywhere in that path);
    # (3) the TF builder as last-resort downloader for egress-ful
    # environments
    kmodels: Dict[str, Any] = {}
    trees: Dict[str, Any] = {}
    embedded_class_index: Optional[str] = None
    for m in models:
        spec = get_model(m)
        variables = init_variables(spec, dtype=engine.dtype)
        npz = npz_sources(m, weights_dir)
        if npz:
            from ..models.params_io import load_npz_fixture

            trees[m], cij = load_npz_fixture(npz[0], variables)
            if cij:
                embedded_class_index = cij
            report["models"][m] = {"weights": f"npz fixture: {npz[0]}"}
            continue
        local = weight_sources(m, weights_dir)
        if local:
            trees[m] = from_keras_h5(local[0], variables)
            report["models"][m] = {"weights": f"h5 (tf-free): {local[0]}"}
            continue
        km, reason = _try_build_keras(m)
        if km is None:
            return {
                "skipped": True,
                "reason": (
                    f"{m}: no fixture .npz at any of "
                    f"{candidate_npz_paths(m, weights_dir)} and no local "
                    f".h5 at any of "
                    f"{candidate_weight_paths(m, weights_dir)} "
                    f"(drop either file there, `put` it into the "
                    f"replicated store and use run_parity_from_store / "
                    f"the `parity-store` CLI verb, or set "
                    f"DML_TPU_KERAS_WEIGHTS_DIR); TF download fallback "
                    f"also failed: {reason}"
                ),
            }
        kmodels[m] = km
        trees[m] = from_keras_model(km, variables)
        report["models"][m] = {"weights": "keras download (tf)"}

    # the goldens carry REAL wnids; without a real class-index table
    # the engine's decode_predictions falls back to synthetic
    # `wnid_%04d` names (models/labels.py) and every golden agreement
    # would read 0% — indistinguishable from a broken converter. Skip
    # with the exact drop-in paths instead of reporting that lie.
    # the staged/extra dir outranks the local search set, mirroring
    # the weights preference order above
    class_index_path = None
    if weights_dir:
        p = os.path.join(weights_dir, "imagenet_class_index.json")
        if os.path.exists(p):
            class_index_path = p
    if class_index_path is None:
        class_index_path = _ensure_class_index()
    tmp_class_index: Optional[str] = None
    if class_index_path is None and embedded_class_index is not None:
        # the npz fixture carries the class index; materialize it so
        # the engine's label table (path-based) can read it (deleted
        # in the finally below — the pinned global is reset with it)
        import tempfile

        fd, class_index_path = tempfile.mkstemp(
            suffix="_imagenet_class_index.json"
        )
        with os.fdopen(fd, "w") as f:
            f.write(embedded_class_index)
        tmp_class_index = class_index_path
    if class_index_path is None:
        return {
            "skipped": True,
            "reason": (
                "imagenet_class_index.json not found at any of "
                f"{candidate_class_index_paths(weights_dir)} and the TF "
                "download fallback failed — drop the stock file (the one "
                "Keras caches) next to the weights or in ~/.keras/models, "
                "`put` it into the replicated store, or use an .npz "
                "fixture with the class index embedded"
            ),
        }
    # make the engine's label table read the file we just located even
    # when it sits outside labels.py's default search set
    from ..models.labels import set_class_index_path

    set_class_index_path(class_index_path)
    try:
        return _validate_models(
            models, engine, trees, kmodels, paths, images, goldens,
            report, class_index_path,
        )
    finally:
        if tmp_class_index is not None:
            # fixture-materialized index: unpin the process-global
            # label path and remove the temp file (all label reads
            # happened during inference above)
            set_class_index_path(None)
            try:
                os.unlink(tmp_class_index)
            except OSError:
                pass


def _validate_models(
    models, engine, trees, kmodels, paths, images, goldens, report,
    class_index_path,
):
    """Serve every model on the goldens' images and score agreement
    (run_parity's validation half, split out so the fixture temp-file
    cleanup wraps it)."""
    import numpy as np

    from ..models import get_model
    from ..models.preprocess import load_images

    ours: Dict[str, Dict[str, List[str]]] = {}
    for m in models:
        engine.load_model(
            m, variables=trees[m], batch_size=8, warmup=False
        )
        res = engine.infer_files(m, [paths[i] for i in images])
        ours[m] = {
            img: [w for (w, _l, _s) in t5]
            for img, t5 in zip(images, res.top5)
        }
        if m not in kmodels:
            # TF-free mode: validation is vs the reference goldens
            # below; live-Keras cross-check needs TF
            continue
        # live Keras on the same decoded uint8 inputs, through Keras's
        # own preprocess_input (the reference's exact path,
        # models.py:23-71)
        from tensorflow import keras as K

        spec = get_model(m)
        raw = load_images([paths[i] for i in images], spec.input_size)
        prep = {
            "ResNet50": K.applications.resnet50.preprocess_input,
            "InceptionV3": K.applications.inception_v3.preprocess_input,
        }[m]
        probs = kmodels[m].predict(
            prep(raw.astype(np.float32)), verbose=0
        )
        idx = np.argsort(probs, axis=-1)[:, ::-1][:, :5]
        if class_index_path:
            with open(class_index_path) as f:
                table = {int(k): v[0] for k, v in json.load(f).items()}
        else:
            table = {i: f"wnid_{i:04d}" for i in range(1000)}
        keras_top = {
            img: [table[int(j)] for j in idx[n]]
            for n, img in enumerate(images)
        }
        report["models"][m]["engine_vs_keras"] = _agreement(
            ours[m], keras_top
        )

    # assign each golden file to the model agreeing with it best
    assignment: Dict[str, str] = {}
    for gname, gdata in goldens.items():
        gold = {img: _top5_wnids(rows) for img, rows in gdata.items()}
        scored = {
            m: _agreement(ours[m], gold)["top1"] for m in models
        }
        best = max(scored, key=lambda m: scored[m])
        assignment[gname] = best
        report["models"][best].setdefault("engine_vs_golden", []).append(
            {"golden": gname, **_agreement(ours[best], gold)}
        )
    report["golden_assignment"] = assignment
    report["class_index"] = bool(class_index_path)
    return report


#: store object names consumed by the store-delivered weights path:
#: pre-converted fixtures, stock Keras .h5s, and the class index —
#: exactly the file names the local search set uses, so one `put`
#: per file feeds every node's parity run
def store_weight_names(models: Sequence[str] = _PARITY_MODELS) -> List[str]:
    names = []
    for m in models:
        names.append(f"dml_tpu_{m}.npz")
        fname = _KERAS_WEIGHT_FILES.get(m)
        if fname:
            names.append(fname)
    names.append("imagenet_class_index.json")
    return names


async def stage_weights_from_store(
    store, dest_dir: str, models: Sequence[str] = _PARITY_MODELS
) -> List[str]:
    """Pull operator-`put` weight files out of the replicated store
    into `dest_dir` (fixtures `dml_tpu_<Model>.npz`, stock Keras
    `.h5`s, `imagenet_class_index.json`). Returns the names fetched;
    missing objects are simply absent — run_parity's normal
    skipped-with-reason path reports what to `put`. Candidate names
    NOT in the store are pruned from `dest_dir`: the staged dir
    mirrors the store, so a file deleted from the store stops feeding
    (and outranking env/cache sources in) future parity runs. One
    listing RPC covers every candidate — per-name ls_all would
    multiply leader-retry stalls on a degraded cluster."""
    os.makedirs(dest_dir, exist_ok=True)
    names = store_weight_names(models)
    try:
        listing = await store.ls_all("*")
    except Exception:
        # a failed LISTING (leaderless window, timeout) is not an
        # empty store: keep the existing mirror untouched rather than
        # pruning files the store still holds
        return []
    fetched = []
    for name in names:
        dest = os.path.join(dest_dir, name)
        if name in listing:
            try:
                await store.get(name, dest)
                fetched.append(name)
            except Exception as e:
                # listed but transiently unfetchable (failover window,
                # data-plane timeout): KEEP any previously staged copy
                # — same reasoning as the listing-failure early return
                log.debug("staged-weights get %s failed: %r", name, e)
        else:
            try:  # genuinely gone from the store: un-mirror it
                os.unlink(dest)
            except OSError:
                pass
    return fetched


async def run_parity_from_store(
    store,
    models: Sequence[str] = _PARITY_MODELS,
    golden_dir: str = GOLDEN_DIR,
    dtype: str = "bfloat16",
) -> Dict[str, Any]:
    """Store-delivered parity (ISSUE 5 satellite): an operator `put`s
    the weight files into the replicated store (see
    `store_weight_names`) and ANY node can produce the parity report —
    no per-host weight drops, no egress. Stages the store objects into
    the node's download dir, then runs the unmodified `run_parity`
    with that directory as the highest-precedence source; the heavy
    sync work runs in a thread so SWIM heartbeats keep flowing."""
    import asyncio

    dest = os.path.join(store.cfg.download_path(), "imagenet_weights")
    fetched = await stage_weights_from_store(store, dest, models)
    report = await asyncio.to_thread(
        run_parity, models=models, golden_dir=golden_dir, dtype=dtype,
        weights_dir=dest,
    )
    report["store_staged"] = fetched
    return report


def main() -> None:
    print(json.dumps(run_parity()))


if __name__ == "__main__":
    main()
