"""Ring vs Ulysses sequence parallelism: measured collective footprint.

`parallel/ulysses.py` states a rule of thumb (prefer ulysses when
heads >= sp and T fits per-device; prefer ring otherwise). This tool
backs it with DATA instead of prose (VERDICT r3 item 10): it compiles
both strategies on a virtual `sp`-device mesh and reads the optimized
HLO — the collectives XLA actually emitted, their counts, and the
bytes each moves — at several (T, heads, sp) points.

What the numbers show (and the rule of thumb predicts):

- ulysses emits a CONSTANT number of all_to_alls (3 in, 1 out per
  attention call) whose combined payload is ~4x one activation,
  regardless of sp;
- ring emits (sp-1) collective-permute ROUNDS, each moving K and V
  blocks — total payload grows with (sp-1)/sp x 2 x activation and
  the round count serializes against compute;
- when heads < sp, ulysses is impossible (heads % sp != 0) and ring
  is the only option — the tool records exactly that.

Run on the CPU mesh (`JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8`); the collective
STRUCTURE in the lowered program is what transfers to the pod — byte
counts are exact, wall-times on a host mesh are not (ICI overlap is
modeled by the compiler, not the host). `python -m
dml_tpu.tools.ring_vs_ulysses` prints the JSON table; bench.py embeds
it in the artifact as `ring_vs_ulysses`.

Net-new vs the reference (no sequence models, SURVEY §0).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict

# dtype -> bytes per element, for HLO shape strings like bf16[2,4096,8,64]
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
    "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}

_COLLECTIVES = (
    "all-to-all", "collective-permute", "all-gather", "all-reduce",
    "reduce-scatter",
)

_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")


def _line_bytes(line: str) -> int:
    """Sum the payload bytes of every result shape on an HLO op line
    (combined ops return tuples: count each member once)."""
    # only the result side (left of the op name) carries the payload;
    # operand shapes repeat it — split at '=' and read the lhs types
    lhs = line.split(")", 1)[0] if line.lstrip().startswith("ROOT") else line
    lhs = lhs.split("=", 1)[-1]
    # stop at the op call to avoid counting operand shapes
    for c in _COLLECTIVES:
        idx = lhs.find(f" {c}(")
        if idx >= 0:
            lhs = lhs[:idx]
            break
    total = 0
    for dtype, dims in _SHAPE_RE.findall(lhs):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_footprint(hlo_text: str) -> Dict[str, Any]:
    """Count collectives and sum their per-device payload bytes in an
    optimized HLO module text."""
    ops: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        for c in _COLLECTIVES:
            # match the op invocation, not stale references/metadata
            if f" {c}(" in line and "=" in line:
                d = ops.setdefault(c, {"count": 0, "mb": 0.0})
                d["count"] += 1
                d["mb"] += _line_bytes(line) / 2**20
                break
    for d in ops.values():
        d["mb"] = round(d["mb"], 2)
    return {
        "ops": ops,
        "total_count": sum(d["count"] for d in ops.values()),
        "total_mb": round(sum(d["mb"] for d in ops.values()), 2),
    }


def analyze_point(
    T: int, heads: int, sp: int, *, head_dim: int = 64, batch: int = 2,
) -> Dict[str, Any]:
    """Compile ring and ulysses attention at one (T, heads, sp) point
    and return each strategy's collective footprint."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..parallel.ring_attention import ring_attention

    devs = jax.devices()
    if len(devs) < sp:
        raise RuntimeError(
            f"need {sp} devices for sp={sp}, have {len(devs)} — run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    mesh = Mesh(
        np.array(devs[:sp]).reshape(1, 1, sp, 1, 1),
        ("dp", "tp", "sp", "pp", "ep"),
    )
    sh = NamedSharding(mesh, P("dp", "sp", None, None))
    shape = (batch, T, heads, head_dim)
    arrs = [
        jax.device_put(jnp.zeros(shape, jnp.bfloat16), sh)
        for _ in range(3)
    ]

    act_mb = batch * (T // sp) * heads * head_dim * 2 / 2**20
    point: Dict[str, Any] = {
        "T": T, "heads": heads, "sp": sp, "head_dim": head_dim,
        "batch": batch,
        "activation_mb_per_device": round(act_mb, 2),
    }

    ring = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=True
    ))
    # static HLO = the loop BODY's collectives counted once; the ring
    # rotation loop executes them sp-1 times, so the dynamic traffic
    # is the static payload x (sp-1) rounds (serialized rounds — each
    # waits for the previous block's KV to arrive)
    ring_static = collective_footprint(
        ring.lower(*arrs).compile().as_text()
    )
    point["ring"] = {
        "hlo_static": ring_static,
        "dynamic_rounds": sp - 1,
        "dynamic_total_mb": round(ring_static["total_mb"] * (sp - 1), 2),
        "note": "collective-permute inside the sp-round rotation loop",
    }

    if heads % sp == 0:
        from ..parallel.ulysses import ulysses_attention

        uly = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh, causal=True
        ))
        # no loop: ulysses' all_to_alls execute exactly once each
        uly_static = collective_footprint(
            uly.lower(*arrs).compile().as_text()
        )
        point["ulysses"] = {
            "hlo_static": uly_static,
            "dynamic_rounds": 1,
            "dynamic_total_mb": uly_static["total_mb"],
            "note": "3 in + 1 out all_to_all, once per attention call",
        }
        point["winner_by_bytes"] = (
            "ulysses"
            if point["ulysses"]["dynamic_total_mb"]
            < point["ring"]["dynamic_total_mb"]
            else "ring"
        )
    else:
        point["ulysses"] = {
            "skipped": f"heads {heads} % sp {sp} != 0 — ulysses "
                       "impossible; ring is the only strategy here",
        }
        point["winner_by_bytes"] = "ring (only option)"
    return point


# the published crossover table: two points where ulysses wins
# (heads >= sp: fewer, bigger collectives) and one where it cannot
# run at all (GQA-ish head count below sp)
POINTS = (
    dict(T=4096, heads=8, sp=8),
    dict(T=8192, heads=16, sp=4),
    dict(T=4096, heads=4, sp=8),
)


def run(points=POINTS) -> Dict[str, Any]:
    return {"points": [analyze_point(**p) for p in points]}


def main() -> None:
    print(json.dumps(run(), indent=2))


if __name__ == "__main__":
    main()
