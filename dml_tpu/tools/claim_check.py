"""Perf-claim hygiene (VERDICT r4 item 7): every performance number in
README.md / PARITY.md PROSE must either trace to the canonical bench
artifact (the file the generated BENCH-TABLE block is stamped with) or
carry an explicit run label.

Round 4 shipped three drifted claims (README "86.5 tok/s" vs artifact
79.6; a punch-list "197.7 q/s" from an unlabeled non-canonical run;
int8-KV prose "1.10×" vs artifact 1.02×) — numbers quoted from
whatever run looked best, not the artifact of record. The generated
table can't drift (sha-stamped, test-enforced); this module extends
the same discipline to prose: a perf number is OK iff

- it appears inside the generated BENCH-TABLE block (already checked
  by test_parity_table.py), or
- it matches an artifact number OF THE SAME KIND within claim
  rounding — × ratios match only ratio-like keys (speedup/gain/
  ratio/vs), MFU percents only mfu-like keys, rates/times any
  numeric leaf (plus rate<->ms conversions). Kind-scoping matters:
  against the artifact's thousands of numbers an unscoped 6%
  tolerance would have PASSED the very 1.10×-vs-1.02 drift this
  tool exists to catch, or
- its line (or its section's heading) carries a run label (``r3``,
  ``round-2``, ``git <sha>``, a ``BENCH_r*`` file name) or quotes
  the reference/baseline — i.e. the reader is told which run the
  number belongs to.

Used by tests/test_claim_hygiene.py; run standalone for a report:

    python -m dml_tpu.tools.claim_check
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Any, Dict, Iterator, List, Optional, Tuple

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# a number immediately followed by a perf unit = a perf claim. The ×
# form catches speedup claims ("1.10×"); percentages only when
# explicitly about MFU/util (bare % is too generic).
_UNIT = (
    r"(?:gen\s+)?tok/s|q/s|img/s|queries/sec|ms/image|ms/step|ms/tok"
    r"|ms\b|µs|MB/s|GB/s|TF/s|MB/slot|×"
)
CLAIM_RE = re.compile(
    rf"(~?)(\d[\d,]*(?:\.\d+)?)\s*(k?)\s*({_UNIT})"
)
MFU_RE = re.compile(r"(\d+(?:\.\d+)?)\s*%\s*(?:fwd\+bwd\s+)?(?:MFU|util)",
                    re.IGNORECASE)

# a line carrying any of these tells the reader which run/source the
# number belongs to — labeled claims are exempt from artifact matching.
# bound/ceiling/ideal need the lookbehind: "HBM-bound"/"control-plane-
# bound" is prose style, not a derivation label (an r4 drifted claim
# sat on exactly such a line)
LABEL_RE = re.compile(
    r"\br[1-9]\b|round[- ][1-9]|git [0-9a-f]{7,}|BENCH_r\d+"
    r"|reference|baseline|CS425|spec peak"
    r"|(?<!-)\b(?:ideal|ceiling|bound)\b"
    r"|roofline|test\.py|worker\.py",
    re.IGNORECASE,
)

RATIO_KEY_RE = re.compile(
    r"speedup|gain|ratio|vs_|pipelining|_x$", re.IGNORECASE
)
MFU_KEY_RE = re.compile(r"mfu|util", re.IGNORECASE)
# rate-like artifact keys (tok/s, q/s, img/s, MB/s...) — rate claims
# match ONLY these: against the unscoped number soup the r4 stale
# "197.7 q/s" false-passed by colliding with params_millions
RATE_KEY_RE = re.compile(
    r"per_s|qps|tok_s|img_s|mb_per|gb_per", re.IGNORECASE
)
TIME_KEY_RE = re.compile(
    r"_ms|ms_|\bms\b|latency|wall_s|_s$|time|detect", re.IGNORECASE
)
SIZE_KEY_RE = re.compile(r"mb|bytes|gb\b", re.IGNORECASE)

GEN_BEGIN = "<!-- BENCH-TABLE:BEGIN"
GEN_END = "<!-- BENCH-TABLE:END -->"


def canonical_artifact_path(parity_path: Optional[str] = None) -> str:
    """The artifact of record = the file PARITY's generated table is
    stamped with (``source=...`` in the BENCH-TABLE marker)."""
    parity_path = parity_path or os.path.join(REPO, "PARITY.md")
    with open(parity_path) as f:
        for line in f:
            m = re.search(r"BENCH-TABLE:BEGIN source=(\S+)", line)
            if m:
                return os.path.join(REPO, m.group(1))
    raise ValueError(f"no BENCH-TABLE source marker in {parity_path}")


def artifact_numbers(path: str) -> Dict[str, List[float]]:
    """Kind-bucketed numeric leaves of the artifact:

    - ``ratio``: values under ratio-like keys (speedup/gain/ratio/vs)
    - ``mfu``: values under mfu/util keys, plus their ×100 percents
    - ``rate``: values under rate-like keys (tok/s, q/s, MB/s...)
    - ``time``: values under time-like keys (ms, latency, wall) plus
      the two honest restatements — 1000/rate (rate -> ms/item) and
      seconds-keys × 1000
    - ``size``: values under MB/bytes keys
    - ``flops``: peak/flops values scaled to TF/s

    Every claim matches only its OWN kind — against the unscoped
    union a stale rate can false-pass by colliding with an unrelated
    leaf (r4's "197.7 q/s" equals the artifact's params_millions).

    The artifact may be a raw bench stdout OR a driver wrapper whose
    tail holds only the compact summary line — parity_table.load_bench
    recovers either form, so the artifact of record can be the driver
    capture itself."""
    from .parity_table import load_bench

    data = load_bench(path)
    buckets: Dict[str, List[float]] = {
        "ratio": [], "mfu": [], "rate": [], "time": [], "size": [],
        "flops": [],
    }

    def walk(x: Any, key: str) -> None:
        if isinstance(x, bool):
            return
        if isinstance(x, (int, float)):
            if not math.isfinite(x):
                return
            v = float(x)
            if RATIO_KEY_RE.search(key):
                buckets["ratio"].append(v)
            if MFU_KEY_RE.search(key):
                buckets["mfu"].append(v)
                buckets["mfu"].append(v * 100.0)
            if RATE_KEY_RE.search(key):
                buckets["rate"].append(v)
            if TIME_KEY_RE.search(key):
                buckets["time"].append(v)
                buckets["time"].append(v * 1000.0)  # s-keyed -> ms
            if SIZE_KEY_RE.search(key):
                buckets["size"].append(v)
            if "flops" in key.lower():
                buckets["flops"].append(v / 1e12)
            return
        if isinstance(x, dict):
            for k, v in x.items():
                walk(v, str(k))
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v, key)

    walk(data, "")
    buckets["time"] += [
        1000.0 / n for n in buckets["rate"] if n > 0
    ]
    return buckets


_UNIT_BUCKET = {
    "×": "ratio", "%MFU": "mfu", "TF/s": "flops", "MB/slot": "size",
    "ms": "time", "µs": "time", "ms/image": "time", "ms/step": "time",
    "ms/tok": "time",
}


def _bucket_for(unit: str) -> str:
    return _UNIT_BUCKET.get(unit, "rate")


def _close(value: float, pool: List[float], rel: float) -> bool:
    return any(
        math.isclose(value, a, rel_tol=rel, abs_tol=1e-9) for a in pool
    )


def _claim_matches(value: float, unit: str, kilo: bool, approx: bool,
                   buckets: Dict[str, List[float]]) -> bool:
    if unit == "×":
        # ratios are quoted to 2-3 sig figs; 2.5% separates 1.10 from
        # 1.02 while passing honest rounding like 1.94 for 1.938. An
        # explicit "~" buys an approximation band ("~2×" for 1.94) —
        # wide, but a genuinely drifted ratio (1.10 for 1.02, or r4's
        # "~100×" README prefill claim vs the artifact's 162.7) still
        # trips it
        return _close(value, buckets["ratio"], 0.12 if approx else 0.025)
    if unit == "%MFU":
        return _close(value, buckets["mfu"], 0.02)
    digits = len(re.sub(r"\D", "", f"{value:g}"))
    rel = 0.03 if (kilo or digits <= 2) else 0.015 if digits == 3 else 0.006
    if approx:
        rel = max(rel, 0.12)
    return _close(value, buckets[_bucket_for(unit)], rel)


def iter_prose_claims(
    path: str,
) -> Iterator[Tuple[int, str, float, str, bool, bool]]:
    """(line_no, line, value, unit, kilo, approx) for every perf claim
    in UNLABELED prose — generated blocks, code fences, and sections
    whose heading carries a run label are skipped."""
    in_gen = False
    in_code = False
    heading_labeled = False
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if GEN_BEGIN in line:
                in_gen = True
            if GEN_END in line:
                in_gen = False
                continue
            if line.strip().startswith("```"):
                in_code = not in_code
                continue
            if in_gen or in_code:
                continue
            if line.startswith("#"):
                # a run label on a heading covers its whole section
                # ("## LM decode analysis (round 4)")
                heading_labeled = bool(LABEL_RE.search(line))
                continue
            if heading_labeled or LABEL_RE.search(line):
                continue
            for m in CLAIM_RE.finditer(line):
                approx, raw, kilo, unit = m.groups()
                v = float(raw.replace(",", ""))
                if kilo:
                    v *= 1000.0
                yield i, line, v, unit, bool(kilo), bool(approx)
            for m in MFU_RE.finditer(line):
                yield i, line, float(m.group(1)), "%MFU", False, False


def check_file(
    path: str, buckets: Dict[str, List[float]]
) -> List[Tuple[int, str, float, str]]:
    """Violations: unlabeled prose perf claims matching nothing of
    their kind in the canonical artifact."""
    bad = []
    for i, line, v, unit, kilo, approx in iter_prose_claims(path):
        if not _claim_matches(v, unit, kilo, approx, buckets):
            bad.append((i, line.rstrip(), v, unit))
    return bad


def run_check(
    artifact_path: Optional[str] = None,
) -> Dict[str, List[Tuple[int, str, float, str]]]:
    buckets = artifact_numbers(
        artifact_path or canonical_artifact_path()
    )
    out = {}
    for name in ("README.md", "PARITY.md"):
        out[name] = check_file(os.path.join(REPO, name), buckets)
    return out


# ----------------------------------------------------------------------
# bench-artifact metrics block (observability.bench_metrics_block)
# ----------------------------------------------------------------------

#: first round whose bench ran with the typed metrics registry; older
#: BENCH_r* artifacts predate it and are exempt from the block check
METRICS_REQUIRED_FROM_ROUND = 6

_ROUND_RE = re.compile(r"BENCH_r(\d+)", re.IGNORECASE)


def artifact_round(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def check_metrics_block(path: str) -> List[str]:
    """Validate that a bench artifact carries the observability
    registry's ``metrics`` block (counters/gauges/histograms summary,
    ``schema`` stamp) — a bench that silently dropped instrumentation
    would otherwise publish headline numbers with no per-stage
    breakdown behind them. Returns a list of problems (empty = OK).

    Artifacts from rounds before ``METRICS_REQUIRED_FROM_ROUND`` are
    exempt (the registry didn't exist); an unnumbered artifact is held
    to the new standard. When the artifact's LM sections actually ran
    (neither skipped by the wall budget nor errored), the lm_server
    decode counters must be nonzero — an instrumented serve that
    counted nothing means the hot path lost its hooks."""
    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < METRICS_REQUIRED_FROM_ROUND:
        return []
    from .parity_table import load_bench

    data = load_bench(path)
    if data.get("_summary_only"):
        # driver-tail compact form: the matrix-level blocks live in
        # the same-round preview; nothing to validate here
        return []
    block = data.get("metrics")
    if not isinstance(block, dict):
        return [f"{name}: no `metrics` block (bench instrumentation "
                "dropped? see observability.bench_metrics_block)"]
    if "error" in block and "counters" not in block:
        return [f"{name}: metrics block capture failed: {block['error']}"]
    problems = []
    for key in ("counters", "gauges", "histograms"):
        if not isinstance(block.get(key), dict):
            problems.append(f"{name}: metrics.{key} missing or not a dict")
    if problems:
        return problems
    for k, h in block["histograms"].items():
        if not isinstance(h, dict) or "count" not in h:
            problems.append(
                f"{name}: metrics.histograms[{k!r}] lacks a count"
            )
            break
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    lm_ran = not {"lm", "cluster_lm_serving"} <= not_run
    if lm_ran and not any(
        k.startswith("lm_server_decode_tokens_total") and v
        for k, v in block["counters"].items()
    ):
        problems.append(
            f"{name}: LM sections ran but lm_server_decode_tokens_total "
            "is zero/absent — the decode path lost its instrumentation"
        )
    return problems


def run_metrics_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_metrics_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# chaos section (bench _bench_chaos / cluster/chaos.py)
# ----------------------------------------------------------------------

#: first round whose bench carries the chaos soak section; earlier
#: artifacts predate the chaos engine and are exempt
CHAOS_REQUIRED_FROM_ROUND = 7

#: first round whose chaos section must ALSO carry the per-family
#: adversarial scenario sweeps (asym/disk/dns/skew/fuzz) and the
#: malformed-drop evidence; earlier artifacts predate them
CHAOS_SCENARIOS_REQUIRED_FROM_ROUND = 8

#: the adversarial families the bench must sweep (mirror of
#: cluster/chaos.py SCENARIO_FAMILIES — kept literal here so this
#: tool stays importable without the cluster stack)
CHAOS_SCENARIO_FAMILIES = ("asym", "disk", "dns", "skew", "fuzz",
                           "churn", "elastic", "liar", "autoscale",
                           "train")

#: "churn" (sustained seeded join/leave) landed with the round-12
#: control-plane scale work; earlier artifacts predate the family
CHAOS_CHURN_REQUIRED_FROM_ROUND = 12

#: "elastic" (authenticated scale-out mid-load, graceful LEAVE,
#: join flapping, forged-join storms) landed with the round-18
#: elastic-membership work; earlier artifacts predate the family
CHAOS_ELASTIC_REQUIRED_FROM_ROUND = 18

#: "liar" (a worker whose self-reported batch walls understate its
#: real walls — the straggler cross-check's adversary) landed with
#: the round-19 signal-plane work; earlier artifacts predate it
CHAOS_LIAR_REQUIRED_FROM_ROUND = 19

#: "autoscale" (controller-aimed chaos: thrashing load, liar-fed
#: policy, scale-in racing a demand spike, leader kill mid-decision)
#: landed with the round-20 autoscaler work; earlier artifacts
#: predate the family
CHAOS_AUTOSCALE_REQUIRED_FROM_ROUND = 20

#: "train" (trainer-aimed chaos: trainer kill mid-epoch, leader kill
#: mid-checkpoint, capacity join racing a step boundary) landed with
#: the round-22 elastic-training work; earlier artifacts predate it
CHAOS_TRAIN_REQUIRED_FROM_ROUND = 22


def check_chaos_block(path: str) -> List[str]:
    """Validate a bench artifact's ``chaos`` section WHEN IT RAN
    (neither wall-budget-skipped nor errored): the invariant sweeps
    must all have passed, and the recovery walls — failover and
    replication repair — must be present, finite, and nonzero. A
    chaos section that 'ran' but recorded no recovery evidence means
    the fault events never actually bit. From round 8 on the section
    must also carry one green sweep per adversarial scenario family
    and, since the fuzz family ran, a nonzero malformed-drop counter
    (a fuzz run that dropped nothing means the byzantine datagrams
    never reached the wire). Returns problems (empty = OK)."""
    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < CHAOS_REQUIRED_FROM_ROUND:
        return []
    from .parity_table import load_bench

    data = load_bench(path)
    if data.get("_summary_only"):
        return []  # matrix-level block lives in the same-round preview
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "chaos" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("chaos")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `chaos` section and not recorded as "
                "skipped (bench lost its chaos soak?)"]
    problems = []
    if not block.get("all_invariants_ok"):
        bad = [s for s in block.get("per_seed", [])
               if not s.get("invariants_ok")]
        problems.append(
            f"{name}: chaos invariant sweep failed for seeds "
            f"{[s.get('seed') for s in bad]}"
        )
    for key in ("failover_recovery_s", "store_repair_s"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: chaos.{key} = {v!r} (recovery wall missing, "
                "nonfinite, or zero — the fault plan never bit)"
            )
    if rnd is not None and rnd < CHAOS_SCENARIOS_REQUIRED_FROM_ROUND:
        return problems
    scenarios = block.get("scenarios")
    if not isinstance(scenarios, dict):
        problems.append(
            f"{name}: chaos.scenarios missing (the adversarial "
            "family sweeps were dropped from the bench?)"
        )
        return problems
    for fam in CHAOS_SCENARIO_FAMILIES:
        if (
            fam == "churn"
            and rnd is not None
            and rnd < CHAOS_CHURN_REQUIRED_FROM_ROUND
        ):
            continue  # the family predates this artifact
        if (
            fam == "elastic"
            and rnd is not None
            and rnd < CHAOS_ELASTIC_REQUIRED_FROM_ROUND
        ):
            continue  # the family predates this artifact
        if (
            fam == "liar"
            and rnd is not None
            and rnd < CHAOS_LIAR_REQUIRED_FROM_ROUND
        ):
            continue  # the family predates this artifact
        if (
            fam == "autoscale"
            and rnd is not None
            and rnd < CHAOS_AUTOSCALE_REQUIRED_FROM_ROUND
        ):
            continue  # the family predates this artifact
        if (
            fam == "train"
            and rnd is not None
            and rnd < CHAOS_TRAIN_REQUIRED_FROM_ROUND
        ):
            continue  # the family predates this artifact
        entry = scenarios.get(fam)
        if not isinstance(entry, dict):
            problems.append(f"{name}: chaos.scenarios[{fam!r}] missing")
        elif not entry.get("all_invariants_ok"):
            bad = [s.get("seed") for s in entry.get("per_seed", [])
                   if not s.get("invariants_ok")]
            problems.append(
                f"{name}: chaos scenario {fam!r} invariant sweep "
                f"failed for seeds {bad}"
            )
    if isinstance(scenarios.get("fuzz"), dict):
        dropped = block.get("malformed_dropped_total")
        if not isinstance(dropped, (int, float)) or dropped <= 0:
            problems.append(
                f"{name}: fuzz scenario ran but "
                f"malformed_dropped_total = {dropped!r} (byzantine "
                "datagrams never hit the transport, or the drop "
                "counter lost its hook)"
            )
    return problems


def run_chaos_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_chaos_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-6 serving fields: adaptive pipeline depth, per-section link
# weather, steady-state LM (bench _bench_cluster_serving /
# _bench_cluster_lm; ISSUE 4 tentpole)
# ----------------------------------------------------------------------

#: first round whose bench carries the adaptive-depth verdict, the
#: in-section link-weather probes on BOTH cluster sections, and the
#: steady-state LM phase; earlier artifacts predate them
SERVING_FIELDS_REQUIRED_FROM_ROUND = 6

#: adaptive-vs-best-static serving ratio below this is a controller
#: that committed to a LOSING depth — more than probe noise can excuse
#: (the r5 failure mode this machinery exists to end was 0.91×)
ADAPTIVE_RATIO_FLOOR = 0.9

#: the steady-state LM phase must cover at least this much post-ramp
#: decode wall, or it is still the transient the r5 verdict rejected
STEADY_MIN_S = 15.0


def _link_weather_ok(section: Dict[str, Any]) -> bool:
    lw = section.get("link_weather_at_section")
    return (
        isinstance(lw, dict)
        and isinstance(lw.get("readback_128kb_ms"), (int, float))
        and isinstance(lw.get("upload_mb_per_s"), (int, float))
    )


def check_serving_block(path: str) -> List[str]:
    """Validate the round-6 serving fields WHEN their sections ran:

    - ``cluster_serving`` and ``cluster_lm_serving`` each carry an
      in-section ``link_weather_at_section`` probe (readback latency +
      upload bandwidth) — a 74.6-vs-220 q/s cross-capture gap must be
      attributable, not asserted;
    - ``cluster_serving.adaptive`` records the depth controller's
      verdict, and ``pipelining_speedup`` (adaptive vs the BETTER
      forced static on the same capture) is not below the probe-noise
      floor — a shipped mode that loses in the artifact of record is
      the r5 failure this exists to end;
    - ``cluster_lm_serving.steady_state`` covers >= ``STEADY_MIN_S``
      of post-ramp decode with a tok/s-vs-wall curve — the transient
      64×32 run cannot distinguish a control-plane ceiling from an
      unwarmed pipeline.

    Artifacts before round 6 are exempt; summary-only driver captures
    are spot-checked at summary level (the full fields live in the
    same-round preview)."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < SERVING_FIELDS_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    problems: List[str] = []
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        ratio = s.get("cluster_pipelining")
        if (
            isinstance(ratio, (int, float))
            and ratio < ADAPTIVE_RATIO_FLOOR
        ):
            problems.append(
                f"{name}: summary cluster_pipelining = {ratio} < "
                f"{ADAPTIVE_RATIO_FLOOR} (adaptive depth lost to a "
                "forced static beyond probe noise)"
            )
        steady = s.get("cluster_lm_steady_s")
        if (
            s.get("cluster_lm_tok_s") is not None
            and isinstance(steady, (int, float))
            and steady < STEADY_MIN_S
        ):
            problems.append(
                f"{name}: summary cluster_lm_steady_s = {steady} < "
                f"{STEADY_MIN_S} (steady-state window too short)"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    cs = matrix.get("cluster_serving")
    if cs is not None and "cluster_serving" not in not_run:
        if not _link_weather_ok(cs):
            problems.append(
                f"{name}: cluster_serving.link_weather_at_section "
                "missing readback/upload (the q/s numbers carry no "
                "attribution for cross-round gaps)"
            )
        ad = cs.get("adaptive")
        if not isinstance(ad, dict) or not isinstance(
            ad.get("depth"), (int, float)
        ):
            problems.append(
                f"{name}: cluster_serving.adaptive verdict missing "
                "(the depth controller's decision was not recorded)"
            )
        ratio = cs.get("pipelining_speedup")
        if not isinstance(ratio, (int, float)) or not math.isfinite(ratio):
            problems.append(
                f"{name}: cluster_serving.pipelining_speedup = "
                f"{ratio!r} (adaptive-vs-best-static ratio missing)"
            )
        elif ratio < ADAPTIVE_RATIO_FLOOR:
            problems.append(
                f"{name}: cluster_serving.pipelining_speedup = {ratio} "
                f"< {ADAPTIVE_RATIO_FLOOR}: the adaptive controller "
                "committed to a depth that loses to a forced static "
                "beyond probe noise"
            )
    clm = matrix.get("cluster_lm_serving")
    if clm is not None and "cluster_lm_serving" not in not_run:
        if not _link_weather_ok(clm):
            problems.append(
                f"{name}: cluster_lm_serving.link_weather_at_section "
                "missing readback/upload"
            )
        ss = clm.get("steady_state")
        if not isinstance(ss, dict):
            problems.append(
                f"{name}: cluster_lm_serving.steady_state missing "
                "(only the transient ran — the r5 gap re-opened)"
            )
        else:
            dur = ss.get("measured_steady_s")
            if not isinstance(dur, (int, float)) or dur < STEADY_MIN_S:
                problems.append(
                    f"{name}: steady_state.measured_steady_s = {dur!r} "
                    f"< {STEADY_MIN_S} (still a transient)"
                )
            rate = ss.get("gen_tok_per_s_steady")
            if not isinstance(rate, (int, float)) or rate <= 0:
                problems.append(
                    f"{name}: steady_state.gen_tok_per_s_steady = "
                    f"{rate!r} (no sustained decode measured)"
                )
            curve = ss.get("curve_tok_per_s")
            if not isinstance(curve, list) or len(curve) < 5:
                problems.append(
                    f"{name}: steady_state.curve_tok_per_s has "
                    f"{len(curve) if isinstance(curve, list) else 0} "
                    "points (< 5: no tok/s-vs-wall shape to read)"
                )
    return problems


def run_serving_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_serving_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# sharded worker-group serving (bench _bench_cluster_sharded /
# jobs/groups.py; ISSUE 5 tentpole)
# ----------------------------------------------------------------------

#: first round whose bench carries the tensor-parallel worker-group
#: serving section; earlier artifacts predate the subsystem
SHARDED_REQUIRED_FROM_ROUND = 7


def check_sharded_block(path: str) -> List[str]:
    """Validate the ``cluster_sharded_serving`` section WHEN IT RAN
    (neither wall-budget-skipped, nor errored, nor honestly recorded
    as skipped-with-reason inside the block):

    - ``equal_outputs`` is True — the param_gather contract: a job
      served by a tp-sharded worker group returns bit-identical
      results to the single-chip path. A False here means sharded
      serving CHANGES ANSWERS and must not ship;
    - ``qps_sharded`` (and the single-chip comparison rate) are
      finite and positive — the serve actually measured something;
    - the group topology is echoed: at least one group with its
      members, primary, and dp/tp mesh, so the artifact records WHAT
      was serving, not just how fast.

    Artifacts before round 7 are exempt; summary-only driver captures
    are gated on the compact line's ``sharded_equal`` flag."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < SHARDED_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        if s.get("sharded_qps") is not None and s.get("sharded_equal") is False:
            return [
                f"{name}: summary sharded_equal is false — group-served "
                "outputs diverged from the single-chip path"
            ]
        return []
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "cluster_sharded_serving" in not_run:
        return []
    block = matrix.get("cluster_sharded_serving")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `cluster_sharded_serving` section and not "
                "recorded as skipped (bench lost the worker-group serve?)"]
    if block.get("skipped"):
        return []  # honest in-block skip (e.g. single-device env)
    problems: List[str] = []
    if block.get("equal_outputs") is not True:
        problems.append(
            f"{name}: cluster_sharded_serving.equal_outputs = "
            f"{block.get('equal_outputs')!r} — tp-sharded group outputs "
            "must be bitwise-equal to the single-chip path"
        )
    for key in ("qps_sharded", "qps_single_chip"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: cluster_sharded_serving.{key} = {v!r} "
                "(missing, nonfinite, or zero — the serve never ran?)"
            )
    groups = block.get("groups")
    ok_topology = isinstance(groups, dict) and any(
        isinstance(g, dict) and g.get("members") and g.get("mesh")
        for g in groups.values()
    )
    if not ok_topology:
        problems.append(
            f"{name}: cluster_sharded_serving.groups does not echo the "
            "group topology (members + dp/tp mesh per group)"
        )
    return problems


def run_sharded_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_sharded_block(artifact_path or canonical_artifact_path())


#: first round whose bench carries the sharded-LM serving section
#: (weight-resident / param_gather / disaggregated on one group)
LM_SHARDED_REQUIRED_FROM_ROUND = 8
#: first round whose bench carries the pipeline-parallel serving form
#: and the chunk-streamed multi-prefill KV handoff ladder
LM_PP_STREAM_REQUIRED_FROM_ROUND = 10


def check_lm_sharded_block(path: str) -> List[str]:
    """Validate the ``cluster_lm_sharded`` section WHEN IT RAN:

    - ``tokens_equal_single_chip`` is True — every serving form's
      merged job outputs must equal isolated generate() per prompt
      (the dryrun tp-decode exactness contract carried end-to-end
      through the cluster). False means sharded LM serving CHANGES
      ANSWERS and must not ship;
    - ``tok_s_param_gather`` / ``tok_s_resident`` / ``tok_s_disagg``
      are finite and positive — all three forms actually served;
    - ``kv_handoff_bytes`` > 0 when the disaggregated form ran with
      any successful handoff — the slab really moved over the data
      plane (a zero here with handoffs recorded means the bench
      measured the fallback path and labeled it disaggregation).

    From round ``LM_PP_STREAM_REQUIRED_FROM_ROUND`` additionally:

    - ``tok_s_pp`` finite and positive (the pipeline-parallel form
      served) with ``hbm.fits_only_pipelined`` True — the recorded
      budget story must actually be "full tree does not fit a
      member, the pp slice does";
    - ``ttft_stream_ms`` finite/positive and
      ``stream_vs_slab_ttft`` > 1 — the chunk-streamed handoff must
      STRICTLY reduce time-to-first-token vs the whole-slab pull on
      the same seed (that overlap is the entire point of streaming);
    - ``fanout_ctx_speedup`` > 1 — two prefill peers must raise
      context-phase throughput over one;
    - the member-kill-mid-stream ``chaos.verdict_green`` is True
      (completed exactly once, tokens unchanged, the kill actually
      felt as typed fallbacks or a degradation edge).

    Artifacts before round 8 are exempt; summary-only driver captures
    gate on the compact line's ``lm_sharded_equal`` flag (and the
    round-10 ``lm_pp_toks`` / ``lm_stream_vs_slab`` keys)."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < LM_SHARDED_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        if (
            s.get("lm_sharded_toks") is not None
            and s.get("lm_sharded_equal") is False
        ):
            problems.append(
                f"{name}: summary lm_sharded_equal is false — group-"
                "sharded LM outputs diverged from isolated generate()"
            )
        if (
            rnd is not None
            and rnd >= LM_PP_STREAM_REQUIRED_FROM_ROUND
            and s.get("lm_sharded_toks") is not None
        ):
            v = s.get("lm_pp_toks")
            if v is not None and (
                not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0
            ):
                problems.append(
                    f"{name}: summary lm_pp_toks = {v!r} (nonfinite "
                    "or zero — the pipeline-parallel form never ran?)"
                )
            r = s.get("lm_stream_vs_slab")
            if r is not None and (
                not isinstance(r, (int, float)) or not r > 1.0
            ):
                problems.append(
                    f"{name}: summary lm_stream_vs_slab = {r!r} — the "
                    "chunk-streamed handoff must strictly reduce TTFT "
                    "vs the whole-slab pull"
                )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "cluster_lm_sharded" in not_run:
        return []
    block = matrix.get("cluster_lm_sharded")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `cluster_lm_sharded` section and not "
                "recorded as skipped (bench lost the sharded-LM serve?)"]
    if block.get("skipped"):
        return []  # honest in-block skip (e.g. single-device env)
    problems: List[str] = []
    if block.get("tokens_equal_single_chip") is not True:
        problems.append(
            f"{name}: cluster_lm_sharded.tokens_equal_single_chip = "
            f"{block.get('tokens_equal_single_chip')!r} — sharded/"
            "disaggregated LM outputs must be token-identical to the "
            "single-chip path"
        )
    for key in ("tok_s_param_gather", "tok_s_resident", "tok_s_disagg"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: cluster_lm_sharded.{key} = {v!r} (missing, "
                "nonfinite, or zero — the serving form never ran?)"
            )
    disagg = (block.get("modes") or {}).get("disagg") or {}
    handoffs = disagg.get("handoffs", 0)
    if handoffs and not block.get("kv_handoff_bytes"):
        problems.append(
            f"{name}: cluster_lm_sharded recorded {handoffs} handoffs "
            "but kv_handoff_bytes is 0/absent — no slab bytes actually "
            "moved over the data plane"
        )
    if block.get("tok_s_disagg") and not handoffs and not disagg.get(
        "fallbacks"
    ):
        problems.append(
            f"{name}: cluster_lm_sharded disagg served with neither "
            "handoffs nor fallbacks recorded — the mode accounting "
            "is broken"
        )
    groups = block.get("groups")
    ok_topology = isinstance(groups, dict) and any(
        isinstance(g, dict) and g.get("members") and g.get("mesh")
        for g in groups.values()
    )
    if not ok_topology:
        problems.append(
            f"{name}: cluster_lm_sharded.groups does not echo the "
            "group topology (members + dp/tp mesh per group)"
        )
    if rnd is not None and rnd >= LM_PP_STREAM_REQUIRED_FROM_ROUND:
        pp_v = block.get("tok_s_pp")
        if not isinstance(pp_v, (int, float)) or not math.isfinite(pp_v) \
                or pp_v <= 0:
            problems.append(
                f"{name}: cluster_lm_sharded.tok_s_pp = {pp_v!r} "
                "(missing, nonfinite, or zero — the pipeline-parallel "
                "form never served)"
            )
        hbm = block.get("hbm") or {}
        if hbm.get("fits_only_pipelined") is not True:
            problems.append(
                f"{name}: cluster_lm_sharded.hbm.fits_only_pipelined "
                f"= {hbm.get('fits_only_pipelined')!r} — the recorded "
                "budget must sit between the pp slice and the full "
                "tree (the models-bigger-than-one-member claim)"
            )
        ttft = block.get("ttft_stream_ms")
        if not isinstance(ttft, (int, float)) or not math.isfinite(ttft) \
                or ttft <= 0:
            problems.append(
                f"{name}: cluster_lm_sharded.ttft_stream_ms = {ttft!r} "
                "(the streamed handoff never recorded a first token)"
            )
        ratio = block.get("stream_vs_slab_ttft")
        if not isinstance(ratio, (int, float)) or not ratio > 1.0:
            problems.append(
                f"{name}: cluster_lm_sharded.stream_vs_slab_ttft = "
                f"{ratio!r} — chunk-streamed handoff must strictly "
                "reduce time-to-first-token vs the whole-slab pull"
            )
        fan = block.get("fanout_ctx_speedup")
        if not isinstance(fan, (int, float)) or not fan > 1.0:
            problems.append(
                f"{name}: cluster_lm_sharded.fanout_ctx_speedup = "
                f"{fan!r} — 2-peer prefill fan-out must raise "
                "context-phase throughput over 1 peer"
            )
        chaos = block.get("chaos") or {}
        if chaos.get("verdict_green") is not True:
            problems.append(
                f"{name}: cluster_lm_sharded.chaos.verdict_green = "
                f"{chaos.get('verdict_green')!r} — the member-kill-"
                "mid-stream case must complete exactly once with "
                "unchanged tokens and a felt kill (typed fallbacks "
                "or a degradation edge)"
            )
    return problems


def run_lm_sharded_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_lm_sharded_block(
        artifact_path or canonical_artifact_path()
    )


#: first round whose bench carries the request front door section
#: (per-request SLO serving under open-loop load, dml_tpu/ingress/)
REQUEST_REQUIRED_FROM_ROUND = 9


def check_request_block(path: str) -> List[str]:
    """Validate the ``request_serving`` section WHEN IT RAN:

    - the sustained-load percentiles (``p50_ms``/``p95_ms``/``p99_ms``)
      are finite, positive, and ordered — the tail was actually
      measured, not defaulted;
    - ``goodput_qps`` is finite and positive, ``shed_ratio`` in
      [0, 1) — a shed ratio of 1.0 means the door rejected everything
      and the 'serving' numbers scored nothing;
    - continuous batch formation beat the naive fixed-size-batch
      baseline on light-load p99 (``continuous_vs_fixed_p99`` > 1)
      while matching its throughput at saturation
      (``saturation_goodput_ratio`` >= 0.8) — the tentpole claim;
    - the leader-failover-mid-traffic case is green:
      ``all_terminal_exactly_once`` True with completions after the
      failover — in-flight requests either complete or are explicitly
      rejected, never silently lost. The verdict is observational
      (zero conflicting late terminals across routers, zero
      completions missing their result payload, completions > 0),
      not an accounting identity.

    Artifacts before round 9 are exempt; summary-only driver captures
    gate on the compact line's ``req_*`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < REQUEST_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        if s.get("req_p99_ms") is not None:
            v = s["req_p99_ms"]
            if not isinstance(v, (int, float)) or not math.isfinite(v) \
                    or v <= 0:
                problems.append(
                    f"{name}: summary req_p99_ms = {v!r} (nonfinite or "
                    "nonpositive)"
                )
            sr = s.get("req_shed_ratio")
            if sr is not None and (
                not isinstance(sr, (int, float)) or not 0 <= sr < 1
            ):
                problems.append(
                    f"{name}: summary req_shed_ratio = {sr!r} not in "
                    "[0, 1)"
                )
            if s.get("req_failover_ok") is False:
                problems.append(
                    f"{name}: summary req_failover_ok is false — a "
                    "request was lost or double-terminated across the "
                    "failover"
                )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "request_serving" in not_run:
        return []
    block = matrix.get("request_serving")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `request_serving` section and not recorded "
                "as skipped (bench lost the front-door serve?)"]
    if block.get("skipped"):
        return []
    problems: List[str] = []
    pcts = []
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: request_serving.{key} = {v!r} (missing, "
                "nonfinite, or zero — the sustained load never served?)"
            )
        else:
            pcts.append(v)
    if len(pcts) == 3 and not (pcts[0] <= pcts[1] <= pcts[2]):
        problems.append(
            f"{name}: request_serving percentiles not ordered "
            f"(p50={pcts[0]}, p95={pcts[1]}, p99={pcts[2]})"
        )
    gp = block.get("goodput_qps")
    if not isinstance(gp, (int, float)) or not math.isfinite(gp) or gp <= 0:
        problems.append(
            f"{name}: request_serving.goodput_qps = {gp!r} (missing, "
            "nonfinite, or zero)"
        )
    sr = block.get("shed_ratio")
    if not isinstance(sr, (int, float)) or not 0 <= sr < 1:
        problems.append(
            f"{name}: request_serving.shed_ratio = {sr!r} not in [0, 1)"
        )
    ratio = block.get("continuous_vs_fixed_p99")
    if not isinstance(ratio, (int, float)) or ratio <= 1.0:
        problems.append(
            f"{name}: request_serving.continuous_vs_fixed_p99 = {ratio!r}"
            " — continuous formation must beat the fixed-batch baseline "
            "on light-load p99"
        )
    sat = block.get("saturation_goodput_ratio")
    if not isinstance(sat, (int, float)) or sat < 0.8:
        problems.append(
            f"{name}: request_serving.saturation_goodput_ratio = {sat!r}"
            " — continuous formation must MATCH fixed-batch throughput "
            "at saturation (>= 0.8)"
        )
    fo = block.get("failover") or {}
    if fo.get("all_terminal_exactly_once") is not True:
        problems.append(
            f"{name}: request_serving.failover.all_terminal_exactly_once"
            f" = {fo.get('all_terminal_exactly_once')!r} — every request "
            "in the failover-mid-traffic run must reach exactly one "
            "terminal"
        )
    if not fo.get("completed", 0):
        problems.append(
            f"{name}: request_serving.failover completed 0 requests — "
            "the cluster never resumed serving after the leader kill"
        )
    if rnd is not None and rnd >= LM_PP_STREAM_REQUIRED_FROM_ROUND:
        # per-class weighted fair share inside the scheduler landed
        # with round 10: the mixed-class rerun must show interactive
        # p99 better under the weighted split than under one FIFO
        cf = block.get("class_fair")
        if not isinstance(cf, dict):
            problems.append(
                f"{name}: request_serving.class_fair missing — the "
                "weighted-vs-FIFO mixed-class rerun never happened"
            )
        elif cf.get("interactive_p99_improved") is not True:
            problems.append(
                f"{name}: request_serving.class_fair."
                "interactive_p99_improved = "
                f"{cf.get('interactive_p99_improved')!r} — weighted "
                "per-class shares must improve interactive p99 over "
                "FIFO under the sustained mixed-class load"
            )
    return problems


def run_request_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_request_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-14 distributed request tracing: the request_serving section
# embeds a `tracing` block (dml_tpu/tracing.py — per-request span
# collection, p99 cohort attribution, deadline-miss exemplars, flight
# recorder budget, sampling-off overhead rerun)
# ----------------------------------------------------------------------

#: first round whose request_serving section must carry the tracing
#: block (cross-node span collection + tail attribution)
TRACING_REQUIRED_FROM_ROUND = 14


def check_tracing_block(path: str) -> List[str]:
    """Validate the ``request_serving.tracing`` block WHEN the section
    ran:

    - ``p99_attrib_ok`` True with ``attributed_fraction`` >= 0.9 — the
      p99 cohort's per-stage breakdown explains at least 90% of its
      measured e2e latency (an attribution that explains less is a
      broken stitch, not an observability layer);
    - ``miss_exemplar_coverage`` == 1.0 — every deadline miss has an
      exemplar trace regardless of the sampling rate (the misses ARE
      the requests that need explaining);
    - the flight recorder stayed within its configured span budget
      (``recorder.within_budget``);
    - the sampling=0 overhead rerun was recorded and its p99 sits
      within noise of the traced run (ratio <= 2.0 — a tracer that
      doubles the tail is measuring itself).

    Artifacts before round ``TRACING_REQUIRED_FROM_ROUND`` are exempt;
    summary-only driver captures gate on the compact line's
    ``trace_p99_attrib_ok`` key."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < TRACING_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        if s.get("trace_p99_attrib_ok") is False:
            return [f"{name}: summary trace_p99_attrib_ok is false — "
                    "the p99 cohort's stage attribution did not explain "
                    ">= 90% of its e2e latency"]
        return []
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "request_serving" in not_run:
        return []
    block = matrix.get("request_serving")
    if block is None or block.get("skipped"):
        return []  # the request gate already flags a missing section
    tb = block.get("tracing")
    if not isinstance(tb, dict):
        if rnd is None:
            return []  # partial/preview artifact
        return [f"{name}: request_serving ran without a `tracing` "
                "block — per-request tracing is required from round "
                f"{TRACING_REQUIRED_FROM_ROUND}"]
    problems: List[str] = []
    if tb.get("p99_attrib_ok") is not True:
        problems.append(
            f"{name}: tracing.p99_attrib_ok = "
            f"{tb.get('p99_attrib_ok')!r} — the p99 cohort's stage "
            "attribution must explain >= 90% of its measured e2e"
        )
    af = (tb.get("p99_attribution") or {}).get("attributed_fraction")
    if not isinstance(af, (int, float)) or not math.isfinite(af) \
            or af < 0.9:
        problems.append(
            f"{name}: tracing attributed_fraction = {af!r} (< 0.9 or "
            "missing)"
        )
    cov = tb.get("miss_exemplar_coverage")
    if not isinstance(cov, (int, float)) or cov < 0.999:
        problems.append(
            f"{name}: tracing.miss_exemplar_coverage = {cov!r} — every "
            "deadline miss must have an exemplar trace (sampling must "
            "not hide the tail)"
        )
    rec = tb.get("recorder") or {}
    if rec.get("within_budget") is not True:
        problems.append(
            f"{name}: tracing.recorder.within_budget = "
            f"{rec.get('within_budget')!r} — the flight recorder "
            "exceeded its configured span budget"
        )
    ov = tb.get("overhead") or {}
    ratio = ov.get("p99_traced_vs_untraced")
    if not isinstance(ratio, (int, float)) or not math.isfinite(ratio) \
            or ratio <= 0:
        problems.append(
            f"{name}: tracing.overhead.p99_traced_vs_untraced = "
            f"{ratio!r} — the sampling=0 overhead rerun was never "
            "measured"
        )
    elif ratio > 2.0:
        problems.append(
            f"{name}: tracing overhead ratio {ratio!r} > 2.0 — tracing "
            "is perturbing the tail it claims to measure"
        )
    return problems


def run_tracing_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_tracing_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-17 KV prefix cache: the request_serving section's multi-turn
# phase (inference/kv_cache.py — warm-start decode from resident KV
# slabs, suffix-only prefill) embeds a `kv_cache` block
# ----------------------------------------------------------------------

#: first round whose request_serving section must carry the kv_cache
#: block (growing-history session trace scored warm vs cold)
KV_CACHE_REQUIRED_FROM_ROUND = 17


def check_kv_cache_block(path: str) -> List[str]:
    """Validate the ``request_serving.kv_cache`` block WHEN the
    section ran:

    - ``hit_ratio`` > 0 — the multi-turn session trace actually
      warm-started (a zero here means session affinity never landed a
      turn on its KV holder, i.e. the locality promise is still
      unfunded);
    - ``warm_vs_cold_ttft`` > 1 — TTFT with the cache strictly beats
      the cold full-re-prefill run of the SAME trace;
    - ``tokens_saved`` > 0 — prompt tokens the suffix-only prefill
      skipped, from the worker-side counter;
    - ``warm_equals_cold`` True — every warm-start completion is
      token-identical to the cold path (the exactness contract);
    - the mid-session leader-failover sub-case ran and stayed
      token-identical too (``failover.warm_equals_cold`` True with
      completions > 0) — relayed session affinity plus exactly-once.

    Artifacts before round ``KV_CACHE_REQUIRED_FROM_ROUND`` are
    exempt; summary-only driver captures gate on the compact line's
    ``kv_hit_ratio`` / ``kv_warm_vs_cold_ttft`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < KV_CACHE_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        hr = s.get("kv_hit_ratio")
        if hr is not None and (
            not isinstance(hr, (int, float)) or not 0 < hr <= 1
        ):
            problems.append(
                f"{name}: summary kv_hit_ratio = {hr!r} — the "
                "multi-turn trace never warm-started"
            )
        rt = s.get("kv_warm_vs_cold_ttft")
        if rt is not None and (
            not isinstance(rt, (int, float)) or not math.isfinite(rt)
            or rt <= 1.0
        ):
            problems.append(
                f"{name}: summary kv_warm_vs_cold_ttft = {rt!r} — "
                "warm TTFT must strictly beat the cold re-prefill"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "request_serving" in not_run:
        return []
    block = matrix.get("request_serving")
    if block is None or block.get("skipped"):
        return []  # the request gate already flags a missing section
    kb = block.get("kv_cache")
    if not isinstance(kb, dict):
        if rnd is None:
            return []  # partial/preview artifact
        return [f"{name}: request_serving ran without a `kv_cache` "
                "block — the multi-turn prefix-cache phase is required "
                f"from round {KV_CACHE_REQUIRED_FROM_ROUND}"]
    problems: List[str] = []
    hr = kb.get("hit_ratio")
    if not isinstance(hr, (int, float)) or not 0 < hr <= 1:
        problems.append(
            f"{name}: kv_cache.hit_ratio = {hr!r} — the session trace "
            "must actually hit the prefix cache (> 0)"
        )
    rt = kb.get("warm_vs_cold_ttft")
    if not isinstance(rt, (int, float)) or not math.isfinite(rt) \
            or rt <= 1.0:
        problems.append(
            f"{name}: kv_cache.warm_vs_cold_ttft = {rt!r} — warm-start "
            "TTFT must strictly beat the cold full-re-prefill run"
        )
    ts = kb.get("tokens_saved")
    if not isinstance(ts, int) or ts <= 0:
        problems.append(
            f"{name}: kv_cache.tokens_saved = {ts!r} — suffix-only "
            "prefill never skipped a prompt token"
        )
    if kb.get("warm_equals_cold") is not True:
        problems.append(
            f"{name}: kv_cache.warm_equals_cold = "
            f"{kb.get('warm_equals_cold')!r} — warm-start completions "
            "must be token-identical to the cold path"
        )
    fo = kb.get("failover")
    if not isinstance(fo, dict):
        problems.append(
            f"{name}: kv_cache.failover missing — the mid-session "
            "leader-kill sub-case never ran"
        )
    else:
        if fo.get("warm_equals_cold") is not True:
            problems.append(
                f"{name}: kv_cache.failover.warm_equals_cold = "
                f"{fo.get('warm_equals_cold')!r} — completions must "
                "stay token-identical across the leader failover"
            )
        if not fo.get("completed", 0):
            problems.append(
                f"{name}: kv_cache.failover completed 0 turns — the "
                "sessions never resumed after the leader kill"
            )
    return problems


def run_kv_cache_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_kv_cache_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# static-analysis verdict: the bench preamble runs tools/dmllint.py and
# records the result; from round 11 on an artifact must say the tree
# is lint-clean (zero un-baselined async-hazard/drift findings) with a
# bounded grandfather baseline
# ----------------------------------------------------------------------

#: first round whose bench carries the dmllint verdict block
LINT_REQUIRED_FROM_ROUND = 11

#: first round whose lint block must ALSO carry the flow-aware pass
#: counts (tools/dmlflow.py: race-yield-hazard + drift-wire-payloads,
#: landed with the round-16 build) — their presence proves both passes
#: ran, and lint_clean covers their findings from that round on
FLOW_LINT_REQUIRED_FROM_ROUND = 16

#: the baseline may only shrink; tests/test_dmllint.py enforces the
#: same bound at tier-1 time, this enforces it on the artifact record
#: (raised 10 -> 25 with the flow-aware rules: justified benign
#: interleavings/echo keys are grandfathered per ISSUE 13)
LINT_BASELINE_MAX = 25


def check_lint_block(path: str) -> List[str]:
    """Validate the ``lint`` preamble block: ``lint_clean`` must be
    True (an artifact built from a tree with un-baselined hazard or
    drift findings is not a clean round), the finding count must be
    recorded, and the grandfather baseline must stay within
    ``LINT_BASELINE_MAX`` entries.

    From round ``FLOW_LINT_REQUIRED_FROM_ROUND`` the block must also
    carry integer ``race_findings`` / ``payload_findings`` counts —
    the proof that the flow-aware passes (race-yield-hazard,
    drift-wire-payloads) ran under lint_clean.

    Artifacts before round ``LINT_REQUIRED_FROM_ROUND`` are exempt;
    summary-only driver captures gate on the compact line's
    ``lint_clean`` key (plus ``lint_race`` / ``lint_payload`` from the
    flow round on)."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < LINT_REQUIRED_FROM_ROUND:
        return []
    flow_required = rnd is not None and rnd >= FLOW_LINT_REQUIRED_FROM_ROUND
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        if s.get("lint_clean") is False:
            return [f"{name}: summary lint_clean is false — the round "
                    "ran on a tree with un-baselined dmllint findings"]
        problems: List[str] = []
        if flow_required:
            for key in ("lint_race", "lint_payload"):
                if not isinstance(s.get(key), int):
                    problems.append(
                        f"{name}: summary {key} = {s.get(key)!r} — the "
                        "flow-aware lint pass counts must ride the "
                        "compact line from round "
                        f"{FLOW_LINT_REQUIRED_FROM_ROUND} on"
                    )
        return problems
    matrix = data.get("matrix", {})
    block = matrix.get("lint")
    if block is None:
        if rnd is None:
            return []  # partial/preview artifact without the preamble
        return [f"{name}: no `lint` block — the bench preamble must "
                "record the dmllint verdict from round "
                f"{LINT_REQUIRED_FROM_ROUND} on"]
    problems: List[str] = []
    if block.get("lint_clean") is not True:
        problems.append(
            f"{name}: lint.lint_clean = {block.get('lint_clean')!r} "
            f"(error: {block.get('error')!r}) — un-baselined dmllint "
            "findings (or a broken linter) at bench time"
        )
    n = block.get("findings")
    if not isinstance(n, int) or n < 0:
        problems.append(
            f"{name}: lint.findings = {n!r} (missing or not a count)"
        )
    b = block.get("baseline_size")
    if not isinstance(b, int) or not 0 <= b <= LINT_BASELINE_MAX:
        problems.append(
            f"{name}: lint.baseline_size = {b!r} — the grandfather "
            f"baseline must hold <= {LINT_BASELINE_MAX} justified "
            "entries (it only ever shrinks)"
        )
    if flow_required:
        for key in ("race_findings", "payload_findings"):
            if not isinstance(block.get(key), int):
                problems.append(
                    f"{name}: lint.{key} = {block.get(key)!r} — the "
                    "flow-aware passes (race-yield-hazard / "
                    "drift-wire-payloads) must record their counts "
                    f"from round {FLOW_LINT_REQUIRED_FROM_ROUND} on"
                )
        rules = block.get("rules")
        if isinstance(rules, list) and not (
                {"race-yield-hazard", "drift-wire-payloads"} <= set(rules)):
            problems.append(
                f"{name}: lint.rules is missing the flow-aware rules — "
                "the verdict does not cover race-yield-hazard / "
                "drift-wire-payloads"
            )
    return problems


def run_lint_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_lint_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-12 control-plane scale: the `control_plane_scale` bench
# section scores the delta-gossip + relay-metrics protocol against
# the reference full-table protocol at N in {16, 64, 128} and sweeps
# a sustained-churn invariant run (bench _bench_control_plane_scale;
# ISSUE 11 tentpole)
# ----------------------------------------------------------------------

#: first round whose bench must carry the control_plane_scale section
SCALE_REQUIRED_FROM_ROUND = 12

#: big-N failure detection may be at most this multiple of small-N
SCALE_DETECT_RATIO_MAX = 1.5


def check_scale_block(path: str) -> List[str]:
    """Validate the ``control_plane_scale`` section WHEN IT RAN:

    - the scored walls (convergence, failure detection, election at
      the biggest N under the delta protocol) are finite and
      positive — a probe that timed out records None and is a
      violation, not a skip;
    - the delta protocol's control-plane bytes/node/s is STRICTLY
      below full-table gossip at every N >= 64 (the tentpole claim);
    - cluster-wide failure detection at the biggest N is within
      ``SCALE_DETECT_RATIO_MAX`` of small-N;
    - the relay metrics-aggregation wall grows sub-linearly in N;
    - the sustained-churn run swept green (exactly one leader, no
      lost store files, no dead coroutines, under continuous
      join/leave).

    Artifacts before round ``SCALE_REQUIRED_FROM_ROUND`` are exempt;
    summary-only driver captures gate on the compact line's
    ``scale_*`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < SCALE_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        for key in ("scale_converge_s", "scale_detect_s",
                    "scale_bytes_per_node_s"):
            v = s.get(key)
            if v is not None and (
                not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0
            ):
                problems.append(
                    f"{name}: summary {key} = {v!r} (nonfinite or "
                    "nonpositive — the scale probe never measured)"
                )
        if s.get("scale_ok") is False:
            problems.append(
                f"{name}: summary scale_ok is false — a control-plane "
                "scale verdict (bytes-below-full / detection-ratio / "
                "metrics-sublinear / churn) failed"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "control_plane_scale" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("control_plane_scale")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `control_plane_scale` section and not "
                "recorded as skipped (bench lost the scale matrix?)"]
    problems: List[str] = []
    for key in ("scale_converge_s", "scale_detect_s",
                "scale_election_s", "scale_bytes_per_node_s"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: control_plane_scale.{key} = {v!r} (missing, "
                "nonfinite, or zero — the big-N probe timed out or "
                "never measured)"
            )
    bvf = block.get("bytes_vs_full_by_n")
    if not isinstance(bvf, dict) or not bvf:
        problems.append(
            f"{name}: control_plane_scale.bytes_vs_full_by_n missing — "
            "the old-vs-new protocol comparison never ran"
        )
    else:
        for n, v in sorted(bvf.items()):
            try:
                big_enough = int(n) >= 64
            except (TypeError, ValueError):
                continue
            if big_enough and (
                not isinstance(v, (int, float)) or not v < 1.0
            ):
                problems.append(
                    f"{name}: control_plane_scale delta/full bytes "
                    f"ratio at N={n} is {v!r} — the delta protocol "
                    "must be strictly below full-table gossip"
                )
    dr = block.get("detect_ratio_vs_small_n")
    if not isinstance(dr, (int, float)) or dr > SCALE_DETECT_RATIO_MAX:
        problems.append(
            f"{name}: control_plane_scale.detect_ratio_vs_small_n = "
            f"{dr!r} — big-N failure detection must stay within "
            f"{SCALE_DETECT_RATIO_MAX}x of small-N"
        )
    mr = block.get("metrics_wall_ratio_vs_small_n")
    ns = block.get("ns") or []
    n_ratio = (
        ns[-1] / ns[0]
        if len(ns) >= 2 and all(isinstance(x, (int, float)) for x in ns)
        and ns[0] else None
    )
    if not isinstance(mr, (int, float)) or (
        n_ratio is not None and mr >= n_ratio
    ):
        problems.append(
            f"{name}: control_plane_scale.metrics_wall_ratio_vs_small_n"
            f" = {mr!r} — the relay metrics-pull wall must grow "
            f"sub-linearly in N (< {n_ratio!r})"
        )
    rvs = block.get("straggler_serial_vs_relay")
    if not isinstance(rvs, (int, float)) or rvs <= 1.5:
        problems.append(
            f"{name}: control_plane_scale.straggler_serial_vs_relay = "
            f"{rvs!r} — with dead peers on the pull list the "
            "aggregated pull must stay bounded by ~one timeout while "
            "the serial shape pays one per straggler (> 1.5x)"
        )
    churn = block.get("churn") or {}
    if churn.get("ok") is not True:
        problems.append(
            f"{name}: control_plane_scale.churn not green "
            f"(failures: {churn.get('failures')!r}) — the sustained "
            "join/leave invariant sweep must pass"
        )
    if not churn.get("crash_restart_pairs", 0):
        problems.append(
            f"{name}: control_plane_scale.churn ran zero crash/restart "
            "pairs — sustained churn never actually churned"
        )
    return problems


def run_scale_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_scale_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-18 elastic capacity: authenticated runtime join/leave must
# RAISE throughput when capacity joins mid-load, with zero restarts
# (bench _bench_elastic; ROADMAP item 2's done-condition)
# ----------------------------------------------------------------------

ELASTIC_REQUIRED_FROM_ROUND = 18

#: scale-out must beat the load-window noise floor, not merely tie it
ELASTIC_GAIN_MIN = 1.05


def check_elastic_block(path: str) -> List[str]:
    """Validate the ``elastic_capacity`` section WHEN IT RAN:

    - both q/s windows measured (finite, positive) and the post-join
      window STRICTLY above the pre-join one (``scaleout_gain`` >
      ``ELASTIC_GAIN_MIN``) — capacity added mid-load must raise
      measured throughput;
    - zero restarts (the gain must be admitted capacity, not a
      bounce);
    - every scale-in was graceful (LEAVE sent, not a silent exit);
    - the forged-join storm moved the typed rejection counters;
    - the end-of-run invariant sweep was green (one leader, files at
      factor, no phantom in any universe, no dead coroutines).

    Artifacts before round ``ELASTIC_REQUIRED_FROM_ROUND`` are
    exempt; summary-only driver captures gate on the compact line's
    ``elastic_*`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < ELASTIC_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        gain = s.get("elastic_scaleout_gain")
        if gain is not None and (
            not isinstance(gain, (int, float))
            or not math.isfinite(gain) or gain <= ELASTIC_GAIN_MIN
        ):
            problems.append(
                f"{name}: summary elastic_scaleout_gain = {gain!r} — "
                "capacity joining mid-load must raise q/s above the "
                f"{ELASTIC_GAIN_MIN} noise floor"
            )
        if s.get("elastic_ok") is False:
            problems.append(
                f"{name}: summary elastic_ok is false — an elastic-"
                "capacity verdict (gain / zero-restarts / graceful "
                "scale-in / storm-rejections / sweep) failed"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "elastic_capacity" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("elastic_capacity")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `elastic_capacity` section and not "
                "recorded as skipped (bench lost the elastic run?)"]
    problems: List[str] = []
    for key in ("qps_before", "qps_after"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            problems.append(
                f"{name}: elastic_capacity.{key} = {v!r} (missing, "
                "nonfinite, or zero — a load window never measured)"
            )
    gain = block.get("scaleout_gain")
    if not isinstance(gain, (int, float)) or not math.isfinite(gain) \
            or gain <= ELASTIC_GAIN_MIN:
        problems.append(
            f"{name}: elastic_capacity.scaleout_gain = {gain!r} — "
            "nodes joining mid-load must RAISE measured throughput "
            f"(> {ELASTIC_GAIN_MIN})"
        )
    if block.get("restarts") != 0:
        problems.append(
            f"{name}: elastic_capacity.restarts = "
            f"{block.get('restarts')!r} — the scale-out gain must "
            "come with zero restarts"
        )
    graceful = block.get("scale_in_graceful")
    if not isinstance(graceful, list) or not graceful \
            or not all(v is True for v in graceful):
        problems.append(
            f"{name}: elastic_capacity.scale_in_graceful = "
            f"{graceful!r} — every scale-in must announce LEAVE"
        )
    storm = block.get("storm") or {}
    if not isinstance(storm, dict) or not storm.get("sent") \
            or not isinstance(storm.get("rejected"), (int, float)) \
            or storm.get("rejected", 0) <= 0:
        problems.append(
            f"{name}: elastic_capacity.storm = {storm!r} — the "
            "forged-join storm must run and move the typed rejection "
            "counters"
        )
    if block.get("sweep_ok") is not True:
        problems.append(
            f"{name}: elastic_capacity invariant sweep not green "
            f"(failures: {block.get('sweep_failures')!r})"
        )
    if block.get("elastic_ok") is not True:
        problems.append(
            f"{name}: elastic_capacity.elastic_ok = "
            f"{block.get('elastic_ok')!r} — the section's own verdict "
            "must be true"
        )
    return problems


def run_elastic_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_elastic_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-19 signal plane: burn-rate alerts must FIRE under chaos
# overload with trace exemplars, the straggler cross-check must catch
# a lying worker, and the alert ledger must survive leader failover
# (bench _bench_signal_plane; ISSUE 16 tentpole)
# ----------------------------------------------------------------------

SIGNAL_REQUIRED_FROM_ROUND = 19


def check_signal_block(path: str) -> List[str]:
    """Validate the ``signal_plane`` section WHEN IT RAN:

    - the chaos-overload arm fired a typed burn-rate alert carrying
      an exemplar trace id (an alert without an exemplar cannot be
      drilled into — the flight recorder hook was lost);
    - the lying-metrics arm flagged the liar via the ACK-observed
      wall cross-check WHILE its self-reported walls stayed clean —
      evidence the detection used the leader's own clock, not the
      worker's word;
    - the failover arm carried a firing alert across a leader kill
      and resolved it on the promoted leader (ledger relay worked);
    - the replay arm produced byte-identical alert streams from the
      same seed (the alert pipeline is deterministic given the same
      observations and clock).

    Artifacts before round ``SIGNAL_REQUIRED_FROM_ROUND`` are
    exempt; summary-only driver captures gate on the compact line's
    ``alert_fired_ok`` / ``liar_flagged_ok`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < SIGNAL_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        if s.get("alert_fired_ok") is False:
            problems.append(
                f"{name}: summary alert_fired_ok is false — chaos "
                "overload never fired a typed burn-rate alert"
            )
        if s.get("liar_flagged_ok") is False:
            problems.append(
                f"{name}: summary liar_flagged_ok is false — the "
                "ACK-wall cross-check missed the lying worker"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "signal_plane" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("signal_plane")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `signal_plane` section and not recorded "
                "as skipped (bench lost the signal-plane run?)"]
    problems: List[str] = []
    if block.get("alert_fired_ok") is not True:
        problems.append(
            f"{name}: signal_plane.alert_fired_ok = "
            f"{block.get('alert_fired_ok')!r} — chaos overload must "
            "fire a typed burn-rate alert"
        )
    ex = block.get("exemplar_trace_id")
    if not isinstance(ex, str) or not ex:
        problems.append(
            f"{name}: signal_plane.exemplar_trace_id = {ex!r} — the "
            "fired alert must carry a flight-recorder exemplar"
        )
    if block.get("liar_flagged_ok") is not True:
        problems.append(
            f"{name}: signal_plane.liar_flagged_ok = "
            f"{block.get('liar_flagged_ok')!r} — the ACK-wall "
            "cross-check must flag the lying worker"
        )
    if block.get("liar_self_report_clean") is not True:
        problems.append(
            f"{name}: signal_plane.liar_self_report_clean = "
            f"{block.get('liar_self_report_clean')!r} — the liar's "
            "self-reported walls must have LOOKED healthy (otherwise "
            "the cross-check proved nothing)"
        )
    if block.get("ledger_survived_ok") is not True:
        problems.append(
            f"{name}: signal_plane.ledger_survived_ok = "
            f"{block.get('ledger_survived_ok')!r} — a firing alert "
            "must survive leader kill and resolve on the promoted "
            "leader"
        )
    if block.get("replay_deterministic_ok") is not True:
        problems.append(
            f"{name}: signal_plane.replay_deterministic_ok = "
            f"{block.get('replay_deterministic_ok')!r} — the same "
            "seed must produce a byte-identical alert stream"
        )
    if block.get("signal_ok") is not True:
        problems.append(
            f"{name}: signal_plane.signal_ok = "
            f"{block.get('signal_ok')!r} — the section's own verdict "
            "must be true"
        )
    return problems


def run_signal_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_signal_block(artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-20 closed-loop autoscaler: the diurnal provisioning duel and
# the decision-stream determinism arm (bench _bench_autoscale;
# ISSUE 17 tentpole)
# ----------------------------------------------------------------------

#: first round whose bench must carry the autoscale section; earlier
#: artifacts predate the controller
AUTOSCALE_REQUIRED_FROM_ROUND = 20


def check_autoscale_block(path: str) -> List[str]:
    """Validate the ``autoscale`` section WHEN IT RAN:

    - the autoscaled arm beat static provisioning on BOTH integrals
      of the shared diurnal trace — SLO-violation minutes AND
      chip-idle minutes (winning only one is the provisioning
      dilemma restated, not dissolved);
    - neither arm restarted a node and both invariant sweeps came
      back green (capacity moved through the authenticated join/
      LEAVE path, never through crashes);
    - the controller actually exercised the loop: at least one
      applied scale-out AND one applied scale-in;
    - the replay arm produced byte-identical decision streams from
      the same snapshot schedule (the decision plane is a pure
      function of its observations).

    Artifacts before round ``AUTOSCALE_REQUIRED_FROM_ROUND`` are
    exempt; summary-only driver captures gate on the compact line's
    ``autoscale_ok`` / ``autoscale_slo_min_saved`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < AUTOSCALE_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        if s.get("autoscale_ok") is False:
            problems.append(
                f"{name}: summary autoscale_ok is false — the "
                "closed-loop arm lost the diurnal duel or the "
                "decision stream went nondeterministic"
            )
        saved = s.get("autoscale_slo_min_saved")
        if isinstance(saved, (int, float)) and saved <= 0:
            problems.append(
                f"{name}: summary autoscale_slo_min_saved = "
                f"{saved!r} — the controller saved no SLO-violation "
                "minutes over static provisioning"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "autoscale" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("autoscale")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `autoscale` section and not recorded "
                "as skipped (bench lost the diurnal duel?)"]
    problems: List[str] = []
    for key in ("autoscale_slo_min_saved", "autoscale_idle_min_saved"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or v <= 0:
            problems.append(
                f"{name}: autoscale.{key} = {v!r} — the closed-loop "
                "arm must beat static on BOTH diurnal integrals"
            )
    for arm in ("static", "autoscaled"):
        sub = block.get(arm) or {}
        if sub.get("restarts") != 0:
            problems.append(
                f"{name}: autoscale.{arm}.restarts = "
                f"{sub.get('restarts')!r} — capacity must move "
                "through join/LEAVE, never crashes"
            )
        if sub.get("sweep_ok") is not True:
            problems.append(
                f"{name}: autoscale.{arm}.sweep_ok = "
                f"{sub.get('sweep_ok')!r} — the post-run invariant "
                "sweep must be green"
            )
    applied = block.get("decisions_applied") or {}
    for kind in ("scale_out", "scale_in"):
        if not applied.get(kind):
            problems.append(
                f"{name}: autoscale.decisions_applied[{kind!r}] = "
                f"{applied.get(kind)!r} — the diurnal trace must "
                "exercise both directions of the loop"
            )
    if block.get("replay_deterministic_ok") is not True:
        problems.append(
            f"{name}: autoscale.replay_deterministic_ok = "
            f"{block.get('replay_deterministic_ok')!r} — the same "
            "snapshot schedule must produce a byte-identical "
            "decision stream"
        )
    if block.get("autoscale_ok") is not True:
        problems.append(
            f"{name}: autoscale.autoscale_ok = "
            f"{block.get('autoscale_ok')!r} — the section's own "
            "verdict must be true"
        )
    return problems


def run_autoscale_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_autoscale_block(
        artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# raw decode speed: speculative decoding + step-granular continuous
# batching (ISSUE 19). The bench's cluster_lm_sharded section grows
# `specdec` and `cb` sub-blocks (inference/lm_sharded.py
# bench_specdec_arm / bench_cb_arm); a round-21+ artifact must show
# the speculative arm beating plain chunked decode token-identically
# at its declared acceptance, the miscalibrated draft auto-disabling
# instead of dragging, and overlap adoption beating the batch-drain
# baseline on p99 TTFT.
# ----------------------------------------------------------------------

SPECDEC_REQUIRED_FROM_ROUND = 21


def check_specdec_block(path: str) -> List[str]:
    """Validate the raw-decode arms inside ``cluster_lm_sharded``
    WHEN THE SECTION RAN:

    - the speculative arm's outputs are token-identical to the plain
      chunked path (greedy verify is exactness-preserving — any drift
      means the verify/commit seam is wrong, not "close enough");
    - measured acceptance lands near the bench's declared rate (the
      oracle proposer's corruption schedule pins it — drift means the
      acceptance accounting lies);
    - steady tok/s speedup > 1 at that acceptance (below break-even
      the feature must auto-disable, not ship);
    - the miscalibrated-draft arm DID auto-disable (reason recorded)
      and still produced exact outputs;
    - the continuous-batching overlap arm strictly beat the
      batch-drain baseline on p99 TTFT with equal outputs.

    Artifacts before round ``SPECDEC_REQUIRED_FROM_ROUND`` are
    exempt; summary-only driver captures gate on the compact line's
    ``lm_specdec_speedup`` / ``lm_specdec_accept`` /
    ``lm_cb_ttft_ms`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < SPECDEC_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        speedup = s.get("lm_specdec_speedup")
        if isinstance(speedup, (int, float)) and speedup <= 1.0:
            problems.append(
                f"{name}: summary lm_specdec_speedup = {speedup!r} — "
                "the speculative arm must beat plain chunked decode "
                "on steady tok/s (below break-even it must disable, "
                "not ship a loss)"
            )
        accept = s.get("lm_specdec_accept")
        if isinstance(accept, (int, float)) and not (
                0.0 < accept <= 1.0):
            problems.append(
                f"{name}: summary lm_specdec_accept = {accept!r} — "
                "measured acceptance must be a fraction in (0, 1]"
            )
        ttft = s.get("lm_cb_ttft_ms")
        if isinstance(ttft, (int, float)) and ttft <= 0:
            problems.append(
                f"{name}: summary lm_cb_ttft_ms = {ttft!r} — the "
                "overlap-adoption arm's p99 TTFT must be a positive "
                "wall-clock measurement"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "cluster_lm_sharded" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("cluster_lm_sharded")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `cluster_lm_sharded` section and not "
                "recorded as skipped (raw-decode arms unproven)"]
    if block.get("skipped") or block.get("error"):
        return []  # section self-reported a skip/error payload
    problems: List[str] = []
    spec = block.get("specdec")
    if not isinstance(spec, dict):
        problems.append(
            f"{name}: cluster_lm_sharded.specdec = {spec!r} — "
            "round-21+ artifacts must carry the speculative-decode "
            "arm"
        )
    else:
        if spec.get("outputs_equal") is not True:
            problems.append(
                f"{name}: specdec.outputs_equal = "
                f"{spec.get('outputs_equal')!r} — greedy speculative "
                "decode must be token-identical to the plain path"
            )
        accept = spec.get("accept_rate")
        declared = spec.get("declared_accept")
        if not isinstance(accept, (int, float)) or not (
                0.0 < accept <= 1.0):
            problems.append(
                f"{name}: specdec.accept_rate = {accept!r} — "
                "measured acceptance must be a fraction in (0, 1]"
            )
        elif isinstance(declared, (int, float)) and abs(
                accept - declared) > 0.15:
            problems.append(
                f"{name}: specdec.accept_rate = {accept!r} vs "
                f"declared_accept = {declared!r} — the oracle arm's "
                "measured acceptance must land near the declared "
                "rate (acceptance accounting drifted)"
            )
        speedup = spec.get("speedup")
        if not isinstance(speedup, (int, float)) or speedup <= 1.0:
            problems.append(
                f"{name}: specdec.speedup = {speedup!r} — the "
                "speculative arm must beat plain chunked decode on "
                "steady tok/s"
            )
        auto = spec.get("auto_disable") or {}
        if auto.get("disabled") is not True:
            problems.append(
                f"{name}: specdec.auto_disable.disabled = "
                f"{auto.get('disabled')!r} — the miscalibrated draft "
                "must trip the break-even guard"
            )
        if auto.get("outputs_equal") is not True:
            problems.append(
                f"{name}: specdec.auto_disable.outputs_equal = "
                f"{auto.get('outputs_equal')!r} — outputs must stay "
                "exact even while a bad draft is being rejected"
            )
        if spec.get("verdict_green") is not True:
            problems.append(
                f"{name}: specdec.verdict_green = "
                f"{spec.get('verdict_green')!r} — the arm's own "
                "verdict must be true"
            )
    cb = block.get("cb")
    if not isinstance(cb, dict):
        problems.append(
            f"{name}: cluster_lm_sharded.cb = {cb!r} — round-21+ "
            "artifacts must carry the continuous-batching arm"
        )
    else:
        if cb.get("outputs_equal") is not True:
            problems.append(
                f"{name}: cb.outputs_equal = "
                f"{cb.get('outputs_equal')!r} — step-boundary "
                "adoption must not perturb decoded tokens"
            )
        ratio = cb.get("drain_vs_overlap_p99")
        if not isinstance(ratio, (int, float)) or ratio <= 1.0:
            problems.append(
                f"{name}: cb.drain_vs_overlap_p99 = {ratio!r} — "
                "overlap adoption must strictly beat the batch-drain "
                "baseline on p99 TTFT under staggered load"
            )
        if cb.get("verdict_green") is not True:
            problems.append(
                f"{name}: cb.verdict_green = "
                f"{cb.get('verdict_green')!r} — the arm's own "
                "verdict must be true"
            )
    return problems


def run_specdec_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_specdec_block(
        artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# round-22 elastic cluster training: TrainJob as a first-class
# workload (jobs/train.py; bench _bench_cluster_training; ISSUE 20
# tentpole). The claim is step-exact elasticity: examples/s must RISE
# when capacity joins mid-run via re-shard at a step boundary (zero
# restarts), no global step lost or double-applied, and the trainer
# must not evict interactive work past its SLO deadline.
# ----------------------------------------------------------------------

#: first round whose bench must carry the cluster_training section;
#: earlier artifacts predate the TrainJob subsystem
TRAIN_REQUIRED_FROM_ROUND = 22


def check_train_block(path: str) -> List[str]:
    """Validate the ``cluster_training`` section WHEN IT RAN:

    - the scaling arm's examples/s strictly rose after capacity
      joined mid-run (``scaleout_gain`` > 1 with a world-growing
      curve) — an elastic trainer that cannot convert joins into
      throughput is elastic in name only;
    - at least one ``join`` re-shard happened at a step boundary and
      zero nodes were restarted to get it (capacity moves through
      the authenticated join path, never through crashes);
    - the post-run invariant sweep came back green — it replays the
      step ledger against the exactly-once oracle, so a green sweep
      IS the no-step-lost/no-step-double-applied proof;
    - the mixed arm kept interactive p99 within its SLO deadline
      while the trainer shared the pool.

    Artifacts before round ``TRAIN_REQUIRED_FROM_ROUND`` are exempt;
    summary-only driver captures gate on the compact line's
    ``train_step_qps`` / ``train_elastic_ok`` keys."""
    from .parity_table import load_bench

    name = os.path.basename(path)
    rnd = artifact_round(path)
    if rnd is not None and rnd < TRAIN_REQUIRED_FROM_ROUND:
        return []
    data = load_bench(path)
    if data.get("_summary_only"):
        s = data.get("summary") or {}
        problems = []
        if s.get("train_elastic_ok") is False:
            problems.append(
                f"{name}: summary train_elastic_ok is false — the "
                "trainer lost a step, failed to scale on join, or "
                "evicted interactive work past its deadline"
            )
        qps = s.get("train_step_qps")
        if isinstance(qps, (int, float)) and qps <= 0:
            problems.append(
                f"{name}: summary train_step_qps = {qps!r} — the "
                "mixed arm's trainer examples/s must be positive"
            )
        return problems
    matrix = data.get("matrix", {})
    not_run = set(matrix.get("_skipped", {})) | set(matrix.get("_errors", {}))
    if "cluster_training" in not_run:
        return []  # honestly recorded as skipped/errored
    block = matrix.get("cluster_training")
    if block is None:
        if rnd is None and "cluster_serving" not in matrix:
            return []  # partial/preview artifact without cluster runs
        return [f"{name}: no `cluster_training` section and not "
                "recorded as skipped (elastic-training claim unproven)"]
    problems: List[str] = []
    gain = block.get("scaleout_gain")
    if not isinstance(gain, (int, float)) or gain <= 1.0:
        problems.append(
            f"{name}: cluster_training.scaleout_gain = {gain!r} — "
            "examples/s must strictly rise after capacity joins "
            "mid-run"
        )
    curve = block.get("scaling_curve")
    if (not isinstance(curve, list) or len(curve) < 2
            or not all(isinstance(p, dict) for p in curve)):
        problems.append(
            f"{name}: cluster_training.scaling_curve = {curve!r} — "
            "the section must record the step-throughput curve "
            "across at least two pool sizes"
        )
    else:
        worlds = [p.get("world") for p in curve]
        if worlds != sorted(worlds) or worlds[-1] <= worlds[0]:
            problems.append(
                f"{name}: cluster_training.scaling_curve worlds = "
                f"{worlds!r} — the data-parallel world must grow "
                "across the curve (joins never re-sharded the run?)"
            )
    if not block.get("join_reshards"):
        problems.append(
            f"{name}: cluster_training.join_reshards = "
            f"{block.get('join_reshards')!r} — at least one join "
            "must land as a step-boundary re-shard"
        )
    if block.get("restarts") != 0:
        problems.append(
            f"{name}: cluster_training.restarts = "
            f"{block.get('restarts')!r} — elasticity must come from "
            "re-sharding, never from restarting nodes"
        )
    if block.get("sweep_ok") is not True:
        problems.append(
            f"{name}: cluster_training.sweep_ok = "
            f"{block.get('sweep_ok')!r} — the invariant sweep replays "
            "the step ledger against the exactly-once oracle; it "
            "must be green"
        )
    mixed = block.get("mixed") or {}
    p99 = mixed.get("interactive_p99_with_trainer_s")
    deadline = mixed.get("interactive_deadline_s")
    if (isinstance(p99, (int, float)) and isinstance(
            deadline, (int, float)) and p99 > deadline):
        problems.append(
            f"{name}: cluster_training.mixed interactive p99 = "
            f"{p99!r}s > deadline {deadline!r}s — the trainer must "
            "not push interactive work past its SLO class"
        )
    if block.get("train_elastic_ok") is not True:
        problems.append(
            f"{name}: cluster_training.train_elastic_ok = "
            f"{block.get('train_elastic_ok')!r} — the section's own "
            "verdict must be true"
        )
    return problems


def run_train_check(artifact_path: Optional[str] = None) -> List[str]:
    return check_train_block(
        artifact_path or canonical_artifact_path())


# ----------------------------------------------------------------------
# artifact-of-record provenance: the PARITY table must not stay
# stamped from a builder preview once the same round's DRIVER capture
# exists and parses (ISSUE 4 satellite; VERDICT r5 item 1)
# ----------------------------------------------------------------------

_PREVIEW_RE = re.compile(r"BENCH_r(\d+)_preview\.json$")


def check_parity_source(parity_path: Optional[str] = None) -> List[str]:
    """Flag a PARITY table whose ``source=`` is a preview while a
    parseable same-round driver capture exists. `latest_bench_path`
    already tie-breaks driver over preview; this makes skipping the
    post-driver re-stamp a visible violation instead of a silent
    dependence on builder-run numbers."""
    from .parity_table import load_bench

    parity_path = parity_path or os.path.join(REPO, "PARITY.md")
    with open(parity_path) as f:
        text = f.read()
    m = re.search(r"BENCH-TABLE:BEGIN source=(\S+)", text)
    if not m:
        return [f"{os.path.basename(parity_path)}: no BENCH-TABLE "
                "source marker"]
    src = m.group(1)
    pm = _PREVIEW_RE.match(os.path.basename(src))
    if not pm:
        return []
    driver = f"BENCH_r{pm.group(1)}.json"
    dpath = os.path.join(os.path.dirname(parity_path) or REPO, driver)
    if not os.path.exists(dpath):
        return []
    if load_bench(dpath).get("_unparseable_wrapper"):
        return []  # driver capture exists but is unrecoverable
    return [
        f"PARITY.md table is stamped from the builder preview {src} "
        f"while the same-round driver capture {driver} exists and "
        f"parses — regenerate: python -m dml_tpu.tools.parity_table "
        f"--bench {driver} --write"
    ]


def main() -> None:
    art_path = canonical_artifact_path()
    print(f"artifact of record: {os.path.basename(art_path)}")
    total = 0
    for name, bad in run_check().items():
        for i, line, v, unit in bad:
            total += 1
            print(f"{name}:{i}: unlabeled {v:g} {unit} not in artifact")
            print(f"    {line[:120]}")
    for problem in run_metrics_check(art_path):
        total += 1
        print(f"metrics block: {problem}")
    for problem in run_chaos_check(art_path):
        total += 1
        print(f"chaos block: {problem}")
    for problem in run_serving_check(art_path):
        total += 1
        print(f"serving block: {problem}")
    for problem in run_sharded_check(art_path):
        total += 1
        print(f"sharded block: {problem}")
    for problem in run_lm_sharded_check(art_path):
        total += 1
        print(f"lm-sharded block: {problem}")
    for problem in run_request_check(art_path):
        total += 1
        print(f"request block: {problem}")
    for problem in run_tracing_check(art_path):
        total += 1
        print(f"tracing block: {problem}")
    for problem in run_kv_cache_check(art_path):
        total += 1
        print(f"kv-cache block: {problem}")
    for problem in run_lint_check(art_path):
        total += 1
        print(f"lint block: {problem}")
    for problem in run_scale_check(art_path):
        total += 1
        print(f"scale block: {problem}")
    for problem in run_elastic_check(art_path):
        total += 1
        print(f"elastic block: {problem}")
    for problem in run_signal_check(art_path):
        total += 1
        print(f"signal block: {problem}")
    for problem in run_autoscale_check(art_path):
        total += 1
        print(f"autoscale block: {problem}")
    for problem in run_specdec_check(art_path):
        total += 1
        print(f"specdec block: {problem}")
    for problem in run_train_check(art_path):
        total += 1
        print(f"train block: {problem}")
    for problem in check_parity_source():
        total += 1
        print(f"parity source: {problem}")
    print(f"{total} violation(s)")
    raise SystemExit(1 if total else 0)


if __name__ == "__main__":
    main()
