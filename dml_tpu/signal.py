"""SLO signal plane: windowed time-series, burn-rate monitors, health
scoring with a straggler cross-check, and a typed cluster alert
lifecycle.

The cluster emits every measurement a closed-loop autoscaler (ROADMAP
item 2) needs — per-class p99/goodput, shed reasons, trace stage
attribution, per-slot weights — but only as point-in-time snapshots.
This module is the sensor layer on top of them, in four coupled
pieces:

- ``MetricWindow`` / ``HistWindow`` / ``WindowSet``: fixed-stride ring
  windows over registry counters/gauges/histograms, sampled on
  explicit ticks with an INJECTED clock, exposing rate / delta /
  trend / windowed-quantile queries. Same clock + same observations ⇒
  identical windows (the seeded-determinism discipline the chaos
  engine and benches rely on everywhere else).
- ``BurnRatePolicy`` / ``BurnRateMonitor``: multi-window (short/long)
  burn-rate evaluation — the Google-SRE shape: the error budget is
  burning only when BOTH windows agree, so a one-tick blip cannot
  fire and a long-dead signal cannot linger — with ``Hysteresis``
  debouncing so a flapping signal cannot oscillate the alert state
  machine.
- ``HealthScorer``: leader-side per-node scoring from ACK evidence.
  Stage-wall z-scores (robust: median + MAD vs the pool) catch honest
  stragglers; the CROSS-CHECK compares each worker's self-reported
  batch wall against the wall the leader itself observed between
  dispatch and ACK — evidence the worker cannot forge, so a
  lying-metrics straggler (the ``liar`` chaos seam injects exactly
  that) is flagged even while its self-reported metrics stay clean.
- ``AlertManager`` + ``SignalPlane``: a CLOSED ``ALERT_NAMES``
  registry (the SPAN_NAMES pattern, lint rule drift-alert-names),
  firing→resolved transitions with dedup + severity + exemplar trace
  ids from the flight recorder, the ``ALERT`` standby relay so the
  ledger survives leader failover, and the ``ALERT_PULL``
  request/reply wire surface the CLI ``health``/``alerts`` verbs read.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import (
    Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple,
)

from .cluster.util import reap_task
from .cluster.wire import Message, MsgType
from .ingress.slo import burn_budget
from .observability import METRICS, hist_quantile
from .tracing import TRACER

log = logging.getLogger(__name__)

# ----------------------------------------------------------------------
# alert-name registry (lint-enforced: dmllint rule drift-alert-names)
# ----------------------------------------------------------------------

#: Every name ``fire_alert(...)`` / ``resolve_alert(...)`` may emit,
#: and therefore every typed condition an operator (or the autoscaler)
#: can subscribe to. tools/dmllint.py cross-checks all literal
#: emission sites in the tree against this tuple — add the name HERE
#: first, or the build fails. Keep the comment on each line: it is the
#: alert catalog.
# plain assignment (no annotation): dmllint's _module_const_strs reads
# top-level Assign nodes, and this tuple IS its machine contract
ALERT_NAMES = (
    "slo_burn_rate",   # an SLO class/model error budget is burning
                       # (multi-window burn-rate breach: deadline-miss
                       # rate, shed ratio, queue-wait trend, or a
                       # model's queue starving with zero ACK flow)
    "node_unhealthy",  # a node's stage walls are a robust-z outlier
                       # vs the pool median (honest straggler)
    "metrics_liar",    # a node's self-reported batch walls disagree
                       # with the leader's own dispatch->ACK
                       # observation (forged-evidence straggler)
)

#: alert severity scale, mildest first
SEVERITIES = ("info", "warning", "critical")

_M_ALERT_FIRED = METRICS.counter(
    "alert_fired_total",
    "alert firing transitions, per name= severity=")
_M_ALERT_RESOLVED = METRICS.counter(
    "alert_resolved_total", "alert resolved transitions, per name=")
_M_ALERT_FIRING = METRICS.gauge(
    "alert_firing", "currently-firing alerts, per name=")
_M_ALERT_RELAYS = METRICS.counter(
    "alert_relays_total",
    "alert ledger transitions relayed leader -> standby")
_M_SIG_SAMPLES = METRICS.counter(
    "signal_samples_total", "signal-plane window sample ticks")
_M_SIG_TRANSITIONS = METRICS.counter(
    "signal_monitor_transitions_total",
    "burn-rate monitor hysteresis transitions, per signal= to=")
_M_SIG_LIAR = METRICS.counter(
    "signal_crosscheck_flags_total",
    "workers newly flagged by the ACK-wall cross-check")


# ----------------------------------------------------------------------
# (a) windowed time-series
# ----------------------------------------------------------------------

class MetricWindow:
    """Fixed-stride ring of ``(bucket_start, value)`` samples.

    ``observe`` replaces the sample in the current stride bucket or
    appends a new one; the deque bound retires buckets older than
    ``width_s``. Values are whatever the caller samples — cumulative
    counter totals (query with ``delta``/``rate``) or point-in-time
    gauge levels (query with ``last``/``trend``). Every query takes
    ``now`` explicitly: the window never reads a wall clock, so the
    same injected clock and the same observations reproduce the same
    answers bit for bit."""

    def __init__(self, width_s: float = 60.0, stride_s: float = 1.0):
        if stride_s <= 0 or width_s < stride_s:
            raise ValueError(
                f"bad window geometry width={width_s} stride={stride_s}"
            )
        self.width_s = float(width_s)
        self.stride_s = float(stride_s)
        self._buckets: Deque[Tuple[float, float]] = deque(
            maxlen=int(math.ceil(width_s / stride_s)) + 1
        )

    def observe(self, now: float, value: float) -> None:
        b = math.floor(now / self.stride_s) * self.stride_s
        if self._buckets:
            last_b = self._buckets[-1][0]
            if b == last_b:
                self._buckets[-1] = (b, float(value))
                return
            if b < last_b:
                return  # non-monotonic clock: drop, never reorder
        self._buckets.append((b, float(value)))

    def _span(
        self, now: float, window_s: Optional[float]
    ) -> List[Tuple[float, float]]:
        w = self.width_s if window_s is None else min(
            float(window_s), self.width_s
        )
        lo = now - w
        return [bv for bv in self._buckets if bv[0] >= lo]

    def last(self) -> Optional[float]:
        return self._buckets[-1][1] if self._buckets else None

    def delta(self, now: float, window_s: Optional[float] = None) -> float:
        """newest − oldest sample inside the window (cumulative
        series: how much the counter moved)."""
        pts = self._span(now, window_s)
        if len(pts) < 2:
            return 0.0
        return pts[-1][1] - pts[0][1]

    def rate(self, now: float, window_s: Optional[float] = None) -> float:
        """``delta`` per second over the covered span (not the nominal
        window: a half-filled window reports the rate it has evidence
        for)."""
        pts = self._span(now, window_s)
        if len(pts) < 2:
            return 0.0
        dt = pts[-1][0] - pts[0][0]
        return (pts[-1][1] - pts[0][1]) / dt if dt > 0 else 0.0

    def trend(self, now: float, window_s: Optional[float] = None) -> float:
        """Least-squares slope (value units per second) over the
        window's samples — the direction a gauge (or a derived
        quantile series) is heading."""
        pts = self._span(now, window_s)
        if len(pts) < 2:
            return 0.0
        n = len(pts)
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [v for _, v in pts]
        mx = sum(xs) / n
        my = sum(ys) / n
        den = sum((x - mx) ** 2 for x in xs)
        if den <= 0:
            return 0.0
        num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
        return num / den

    def to_dict(self) -> Dict[str, Any]:
        return {
            "width_s": self.width_s,
            "stride_s": self.stride_s,
            "samples": [[t, v] for t, v in self._buckets],
        }


class HistWindow:
    """Ring of CUMULATIVE histogram states; windowed quantiles come
    from diffing the cumulative bucket counts at the window's two ends
    and handing the difference to ``observability.hist_quantile``.
    min/max are taken from the newest state (a cumulative histogram
    cannot un-see an extreme), which only widens the clamp — the
    bucket walk stays window-accurate."""

    def __init__(
        self,
        edges: Sequence[float],
        width_s: float = 60.0,
        stride_s: float = 1.0,
    ):
        self.edges = [float(e) for e in edges]
        self._ring = MetricWindow(width_s=width_s, stride_s=stride_s)
        # bucket states ride alongside the scalar ring keyed by the
        # same stride bucket (the scalar value is the cumulative count,
        # which delta() queries can reuse directly)
        self._states: Dict[float, Dict[str, Any]] = {}

    def observe(
        self,
        now: float,
        count: float,
        total: float,
        bkt: Dict[str, float],
        mn: Optional[float] = None,
        mx: Optional[float] = None,
    ) -> None:
        self._ring.observe(now, count)
        live = {b for b, _ in self._ring._buckets}
        b = math.floor(now / self._ring.stride_s) * self._ring.stride_s
        if b in live:
            self._states[b] = {
                "count": float(count), "sum": float(total),
                "bkt": dict(bkt), "min": mn, "max": mx,
            }
        for k in [k for k in self._states if k not in live]:
            del self._states[k]

    def window_entry(
        self, now: float, window_s: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        pts = self._ring._span(now, window_s)
        if not pts:
            return None
        newest = self._states.get(pts[-1][0])
        if newest is None:
            return None
        oldest = self._states.get(pts[0][0]) if len(pts) > 1 else None
        base = oldest or {"count": 0.0, "sum": 0.0, "bkt": {}}
        dcount = newest["count"] - base["count"]
        if dcount <= 0:
            return None
        dbkt = {}
        for k, v in newest["bkt"].items():
            d = v - base["bkt"].get(k, 0.0)
            if d > 0:
                dbkt[k] = d
        return {
            "count": dcount,
            "sum": newest["sum"] - base["sum"],
            "edges": list(self.edges),
            "bkt": dbkt,
            "min": newest.get("min"),
            "max": newest.get("max"),
        }

    def quantile(
        self, q: float, now: float, window_s: Optional[float] = None
    ) -> Optional[float]:
        entry = self.window_entry(now, window_s)
        if entry is None:
            return None
        return hist_quantile(entry, q)


class WindowSet:
    """Named windows over registry metrics, sampled on explicit
    ``sample(now)`` ticks. Readers are plain callables (usually bound
    to a registry metric's ``value``/``items``), so the set works
    identically against the live registry and against a recorded
    observation dict in a deterministic replay.

    ``publish()`` is the registry hook: it registers a collector
    (``MetricsRegistry.add_collector``, weakly held) that refreshes a
    small ``signal_window_value`` gauge family at every exposition, so
    METRICS_PULL / Prometheus text see the windows' latest levels
    without the signal plane pushing anything."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        width_s: float = 120.0,
        stride_s: float = 0.5,
    ):
        self._clock = clock
        self.width_s = float(width_s)
        self.stride_s = float(stride_s)
        self._readers: Dict[str, Callable[[], Optional[float]]] = {}
        self._windows: Dict[str, MetricWindow] = {}
        self._hist_readers: Dict[
            str, Callable[[], Optional[Tuple[float, float, Dict[str, float],
                                             Optional[float],
                                             Optional[float]]]]
        ] = {}
        self._hists: Dict[str, HistWindow] = {}
        self._published = False

    def now(self) -> float:
        return self._clock()

    def track(
        self, key: str, reader: Callable[[], Optional[float]]
    ) -> MetricWindow:
        w = self._windows.get(key)
        if w is None:
            w = self._windows[key] = MetricWindow(
                width_s=self.width_s, stride_s=self.stride_s
            )
            self._readers[key] = reader
        return w

    def track_hist(
        self,
        key: str,
        edges: Sequence[float],
        reader: Callable[[], Optional[Tuple[float, float, Dict[str, float],
                                            Optional[float],
                                            Optional[float]]]],
    ) -> HistWindow:
        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = HistWindow(
                edges, width_s=self.width_s, stride_s=self.stride_s
            )
            self._hist_readers[key] = reader
        return h

    def window(self, key: str) -> Optional[MetricWindow]:
        return self._windows.get(key)

    def hist(self, key: str) -> Optional[HistWindow]:
        return self._hists.get(key)

    def sample(self, now: Optional[float] = None) -> float:
        """One tick: read every tracked reader into its window.
        Returns the tick time (injected clock unless given)."""
        t = self._clock() if now is None else float(now)
        for key, reader in self._readers.items():
            try:
                v = reader()
            except Exception:
                log.debug("window reader %s failed", key, exc_info=True)
                continue
            if v is not None:
                self._windows[key].observe(t, float(v))
        for key, reader in self._hist_readers.items():
            try:
                state = reader()
            except Exception:
                log.debug("hist reader %s failed", key, exc_info=True)
                continue
            if state is not None:
                count, total, bkt, mn, mx = state
                self._hists[key].observe(t, count, total, bkt, mn, mx)
        return t

    def publish(self) -> None:
        if self._published:
            return
        self._published = True
        METRICS.gauge(
            "signal_window_value",
            "latest windowed sample per tracked signal, per key=")
        METRICS.add_collector(self._collect)

    def _collect(self) -> None:
        g = METRICS.gauge("signal_window_value")
        for key, w in self._windows.items():
            v = w.last()
            if v is not None:
                g.set(v, key=key)

    def to_dict(self) -> Dict[str, Any]:
        return {k: w.to_dict() for k, w in sorted(self._windows.items())}


# ----------------------------------------------------------------------
# (b) burn-rate monitors
# ----------------------------------------------------------------------

@dataclass(frozen=True)
class BurnRatePolicy:
    """One monitor's knobs.

    ``budget``      allowed bad fraction (0.02 = 2% of requests may
                    miss/shed before the budget is spent at burn 1.0).
    ``short_s``/``long_s``  the two evaluation windows; BOTH must
                    breach to fire and BOTH must clear to resolve.
    ``fire_burn``/``clear_burn``  the hysteresis band: burn ≥
                    fire_burn breaches, burn ≤ clear_burn clears,
                    in between holds state (a signal flapping inside
                    the band cannot oscillate the alert).
    ``fire_after``/``clear_after``  consecutive evaluations required
                    for each transition (time-domain debounce on top
                    of the band).
    ``min_events``  below this many events in a window the ratio is
                    treated as 0 — zero-traffic denominators (total
                    outage, idle cluster) must read as "not burning",
                    not NaN (the loadgen degenerate-input discipline).
    """

    budget: float = 0.02
    short_s: float = 10.0
    long_s: float = 60.0
    fire_burn: float = 1.0
    clear_burn: float = 0.5
    fire_after: int = 2
    clear_after: int = 3
    min_events: int = 8


class Hysteresis:
    """Debounced two-state machine. ``update(breach)`` takes True
    (breaching), False (clear) or None (inside the band: hold state,
    reset streaks) and returns ``"fire"`` / ``"resolve"`` on the
    debounced transition, else None."""

    def __init__(self, fire_after: int = 2, clear_after: int = 3):
        self.fire_after = max(1, int(fire_after))
        self.clear_after = max(1, int(clear_after))
        self.firing = False
        self._breach_streak = 0
        self._clear_streak = 0

    def update(self, breach: Optional[bool]) -> Optional[str]:
        if breach is None:
            self._breach_streak = 0
            self._clear_streak = 0
            return None
        if breach:
            self._clear_streak = 0
            self._breach_streak += 1
            if not self.firing and self._breach_streak >= self.fire_after:
                self.firing = True
                return "fire"
            return None
        self._breach_streak = 0
        self._clear_streak += 1
        if self.firing and self._clear_streak >= self.clear_after:
            self.firing = False
            return "resolve"
        return None


class BurnRateMonitor:
    """Multi-window burn-rate over one bad/total cumulative pair (or
    pre-computed burn numbers via ``evaluate_burns`` — the queue-wait
    trend signal maps its slope onto the same scale)."""

    def __init__(self, policy: Optional[BurnRatePolicy] = None):
        self.policy = policy or BurnRatePolicy()
        self.hyst = Hysteresis(self.policy.fire_after,
                               self.policy.clear_after)
        self.last: Dict[str, Any] = {}

    def _burn(
        self, now: float, bad: MetricWindow, total: MetricWindow,
        window_s: float,
    ) -> float:
        p = self.policy
        dt = total.delta(now, window_s)
        if dt < p.min_events:
            return 0.0
        db = max(0.0, bad.delta(now, window_s))
        return (db / dt) / p.budget if dt > 0 else 0.0

    def evaluate(
        self, now: float, bad: MetricWindow, total: MetricWindow
    ) -> Optional[str]:
        p = self.policy
        return self.evaluate_burns(
            now,
            self._burn(now, bad, total, p.short_s),
            self._burn(now, bad, total, p.long_s),
        )

    def evaluate_burns(
        self, now: float, burn_short: float, burn_long: float
    ) -> Optional[str]:
        p = self.policy
        if burn_short >= p.fire_burn and burn_long >= p.fire_burn:
            breach: Optional[bool] = True
        elif burn_short <= p.clear_burn and burn_long <= p.clear_burn:
            breach = False
        else:
            breach = None
        self.last = {
            "t": now,
            "burn_short": round(burn_short, 4),
            "burn_long": round(burn_long, 4),
            "firing": self.hyst.firing,
        }
        trans = self.hyst.update(breach)
        if trans is not None:
            self.last["firing"] = self.hyst.firing
        return trans


# ----------------------------------------------------------------------
# (c) health scoring + straggler cross-check
# ----------------------------------------------------------------------

def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class HealthScorer:
    """Leader-side per-node health from the two ACK evidence streams.

    ``observe_ack`` records, per worker, (a) the self-reported batch
    exec wall normalized per item — the z-score input — and (b) the
    pair (leader-OBSERVED dispatch→ACK wall, self-REPORTED exec wall)
    — the cross-check input. The z-score is robust (median + MAD with
    a floored sigma, so a near-constant pool cannot manufacture
    outliers); the cross-check flags a worker whose observed wall
    exceeds its reported wall by both a ratio and an absolute margin
    over a median of ≥ ``min_samples`` ACKs — one slow datagram can't
    convict, and a liar can't talk its way out because the observed
    side is the leader's own clock."""

    def __init__(
        self,
        ratio: float = 1.4,
        abs_margin_s: float = 0.25,
        min_samples: int = 4,
        z_fire: float = 3.0,
        keep: int = 64,
    ):
        self.ratio = float(ratio)
        self.abs_margin_s = float(abs_margin_s)
        self.min_samples = int(min_samples)
        self.z_fire = float(z_fire)
        self._keep = int(keep)
        self._walls: Dict[str, Deque[float]] = {}
        self._pairs: Dict[str, Deque[Tuple[float, float]]] = {}

    def observe_ack(
        self,
        worker: str,
        observed_s: float,
        reported_s: float,
        n_items: int = 1,
    ) -> None:
        per_item = float(reported_s) / max(1, int(n_items))
        self._walls.setdefault(
            worker, deque(maxlen=self._keep)
        ).append(per_item)
        self._pairs.setdefault(
            worker, deque(maxlen=self._keep)
        ).append((float(observed_s), float(reported_s)))

    def forget(self, worker: str) -> None:
        self._walls.pop(worker, None)
        self._pairs.pop(worker, None)

    def crosscheck(self, worker: str) -> Optional[Dict[str, Any]]:
        """Evidence dict if ``worker`` looks like a liar, else None.

        Evaluated over the NEWEST ``2*min_samples`` ACKs, not the whole
        retention deque: a worker that turns liar mid-run must be
        convictable within a bounded number of fresh ACKs instead of
        having to outvote its own honest history (the deque's full
        depth still feeds the z-scores, where history is the point)."""
        rows = self._pairs.get(worker)
        if not rows or len(rows) < self.min_samples:
            return None
        recent = list(rows)[-(2 * self.min_samples):]
        obs_med = _median([o for o, _ in recent])
        rep_med = _median([r for _, r in recent])
        if obs_med > rep_med * self.ratio + self.abs_margin_s:
            return {
                "observed_s": round(obs_med, 4),
                "reported_s": round(rep_med, 4),
                "samples": len(recent),
            }
        return None

    def liars(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for w in self._pairs:
            ev = self.crosscheck(w)
            if ev is not None:
                out[w] = ev
        return out

    def zscores(self) -> Dict[str, float]:
        """Robust z per worker: its recent median per-item wall vs the
        pool median, scaled by MAD (floored so a uniform pool reads
        z≈0 everywhere instead of dividing by ~0)."""
        meds = {
            w: _median(list(vals))
            for w, vals in self._walls.items() if vals
        }
        if len(meds) < 3:
            return {w: 0.0 for w in meds}
        pool = _median(list(meds.values()))
        mad = _median([abs(v - pool) for v in meds.values()])
        sigma = max(mad / 0.6745, 0.1 * pool, 1e-3)
        return {w: (v - pool) / sigma for w, v in meds.items()}

    def scores(self) -> Dict[str, Dict[str, Any]]:
        """Per-node health rollup: 1.0 = healthy; z outliers lose
        score proportionally; a cross-check liar scores 0 (its
        self-reported walls are untrustworthy by construction)."""
        zs = self.zscores()
        liars = self.liars()
        out: Dict[str, Dict[str, Any]] = {}
        for w in sorted(set(zs) | set(liars)):
            z = zs.get(w, 0.0)
            score = max(0.0, 1.0 - max(0.0, z) / (2 * self.z_fire))
            row: Dict[str, Any] = {
                "score": round(0.0 if w in liars else score, 3),
                "z": round(z, 3),
                "liar": w in liars,
                "samples": len(self._walls.get(w, ())),
            }
            if w in liars:
                row["crosscheck"] = liars[w]
            out[w] = row
        return out


# ----------------------------------------------------------------------
# (d) typed alert lifecycle
# ----------------------------------------------------------------------

class AlertManager:
    """Leader-resident alert ledger with firing→resolved transitions,
    dedup, severity, exemplar trace ids, and an append-only event
    stream.

    Determinism contract: with an injected clock, the same sequence of
    ``fire_alert``/``resolve_alert`` calls produces a byte-identical
    ``stream_json()`` — the bench replays a recorded observation
    schedule through fresh monitors + a fresh manager twice and
    compares the bytes. ``adopt`` merges relayed rows so a promoted
    leader inherits the dead leader's firing alerts and can still
    resolve them."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_alerts: int = 256,
        max_events: int = 1024,
    ):
        self._clock = clock
        self.max_alerts = int(max_alerts)
        self._alerts: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._events: Deque[Dict[str, Any]] = deque(maxlen=int(max_events))
        self._seq = 0
        #: transition observers, called as cb(event, row); must not
        #: raise (guarded) — the SignalPlane's standby relay rides this
        self.on_transition: List[
            Callable[[Dict[str, Any], Dict[str, Any]], None]
        ] = []

    @staticmethod
    def _key(name: str, labels: Optional[Dict[str, Any]]) -> str:
        return name + "|" + json.dumps(
            labels or {}, sort_keys=True, separators=(",", ":")
        )

    def _now(self, now: Optional[float]) -> float:
        return self._clock() if now is None else float(now)

    def _check(self, name: str) -> None:
        if name not in ALERT_NAMES:
            raise ValueError(
                f"unregistered alert name {name!r}; add it to "
                f"signal.ALERT_NAMES (and the alert catalog) first"
            )

    def _emit(self, event: Dict[str, Any], row: Dict[str, Any]) -> None:
        self._events.append(event)
        for cb in list(self.on_transition):
            try:
                cb(event, row)
            except Exception:
                log.exception("alert transition observer failed")

    def _gauge_sync(self) -> None:
        counts: Dict[str, int] = {n: 0 for n in ALERT_NAMES}
        for row in self._alerts.values():
            if row["state"] == "firing":
                counts[row["name"]] = counts.get(row["name"], 0) + 1
        for n, c in counts.items():
            _M_ALERT_FIRING.set(c, name=n)

    def _bound(self) -> None:
        while len(self._alerts) > self.max_alerts:
            victim = next(
                (k for k, r in self._alerts.items()
                 if r["state"] == "resolved"),
                next(iter(self._alerts)),
            )
            del self._alerts[victim]

    def fire_alert(
        self,
        name: str,
        labels: Optional[Dict[str, Any]] = None,
        *,
        severity: str = "warning",
        summary: str = "",
        exemplar: Optional[str] = None,
        now: Optional[float] = None,
    ) -> bool:
        """Raise (or refresh) an alert. Returns True on a firing
        TRANSITION; a dedup hit on an already-firing alert bumps its
        count/last and returns False."""
        self._check(name)
        if severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r}")
        t = self._now(now)
        key = self._key(name, labels)
        row = self._alerts.get(key)
        if row is not None and row["state"] == "firing":
            row["count"] += 1
            row["last"] = t
            if severity == "critical":
                row["severity"] = severity
            if exemplar and not row.get("exemplar"):
                row["exemplar"] = exemplar
            return False
        self._seq += 1
        row = {
            "name": name,
            "labels": dict(labels or {}),
            "state": "firing",
            "severity": severity,
            "summary": summary,
            "since": t,
            "last": t,
            "count": (row["count"] + 1) if row else 1,
            "seq": self._seq,
            "exemplar": exemplar,
        }
        self._alerts[key] = row
        self._alerts.move_to_end(key)
        self._bound()
        _M_ALERT_FIRED.inc(name=name, severity=severity)
        self._gauge_sync()
        self._emit(
            {"seq": self._seq, "t": t, "event": "fire", "name": name,
             "labels": dict(labels or {}), "severity": severity,
             "summary": summary, "exemplar": exemplar},
            row,
        )
        return True

    def resolve_alert(
        self,
        name: str,
        labels: Optional[Dict[str, Any]] = None,
        *,
        summary: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Resolve a firing alert. Returns True on the resolved
        transition; resolving an unknown or already-resolved alert is
        a no-op (idempotent across retries and failovers)."""
        self._check(name)
        key = self._key(name, labels)
        row = self._alerts.get(key)
        if row is None or row["state"] != "firing":
            return False
        t = self._now(now)
        self._seq += 1
        row["state"] = "resolved"
        row["last"] = t
        row["seq"] = self._seq
        if summary:
            row["summary"] = summary
        _M_ALERT_RESOLVED.inc(name=name)
        self._gauge_sync()
        self._emit(
            {"seq": self._seq, "t": t, "event": "resolve", "name": name,
             "labels": dict(labels or {}),
             "severity": row["severity"], "summary": row["summary"],
             "exemplar": row.get("exemplar")},
            row,
        )
        return True

    def is_firing(
        self, name: str, labels: Optional[Dict[str, Any]] = None
    ) -> bool:
        row = self._alerts.get(self._key(name, labels))
        return row is not None and row["state"] == "firing"

    def active(self) -> List[Dict[str, Any]]:
        return sorted(
            (dict(r) for r in self._alerts.values()
             if r["state"] == "firing"),
            key=lambda r: r["seq"],
        )

    def rows(self) -> List[Dict[str, Any]]:
        return sorted(
            (dict(r) for r in self._alerts.values()),
            key=lambda r: r["seq"],
        )

    def stream(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def stream_json(self) -> bytes:
        """Canonical serialization of the event stream — the byte-
        identical determinism surface the bench compares."""
        return json.dumps(
            self.stream(), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")

    def adopt(self, rows: Sequence[Dict[str, Any]]) -> int:
        """Merge relayed ledger rows (standby side of the ALERT relay;
        also the promoted leader's inheritance path). Newest-wins by
        the row's ``last`` stamp; malformed rows and unregistered
        names are dropped, not raised — the relay rides fire-and-
        forget datagrams. Returns rows adopted."""
        n = 0
        for row in rows:
            if not isinstance(row, dict):
                continue
            name = row.get("name")
            if name not in ALERT_NAMES:
                continue
            if row.get("state") not in ("firing", "resolved"):
                continue
            labels = row.get("labels")
            if labels is not None and not isinstance(labels, dict):
                continue
            key = self._key(name, labels)
            cur = self._alerts.get(key)
            if cur is not None and cur.get("last", 0) >= row.get("last", 0):
                continue
            adopted = dict(row)
            adopted["labels"] = dict(labels or {})
            self._seq = max(self._seq, int(adopted.get("seq", 0)))
            self._alerts[key] = adopted
            self._alerts.move_to_end(key)
            n += 1
        if n:
            self._bound()
            self._gauge_sync()
        return n


# ----------------------------------------------------------------------
# the plane: composition + wire surface
# ----------------------------------------------------------------------

# registry handles the window set samples. Get-or-create by name is
# idempotent, so these bind to the SAME objects the router/jobs
# modules registered (or pre-register them in import orders where the
# signal plane loads first).
_M_REQ_ADMITTED = METRICS.counter(
    "request_admitted_total",
    "requests admitted at the front door, per class")
_M_REQ_SHED = METRICS.counter(
    "request_shed_total",
    "requests shed at admission with a typed rejection, per class+reason")
_M_REQ_COMPLETED = METRICS.counter(
    "request_completed_total", "requests completed, per class")
_M_REQ_MISS = METRICS.counter(
    "request_deadline_miss_total",
    "completions that landed past their SLO deadline, per class")
_M_REQ_QWAIT = METRICS.histogram(
    "request_queue_wait_seconds",
    "admission -> batch dispatch wait, per class")
_M_COORD_ACKS = METRICS.counter(
    "coordinator_batch_acks_total",
    "worker batch ACKs processed by the coordinator, per model")


def _labeled_sum(metric: Any, **match: str) -> float:
    """Sum a metric's children whose label set contains ``match``."""
    want = set(match.items())
    total = 0.0
    for key, val in metric.items():
        if want.issubset(set(key)):
            total += float(val)
    return total


def _label_values(metric: Any, label: str) -> List[str]:
    """Distinct values of ``label`` across a metric's children."""
    vals = set()
    for key, _ in metric.items():
        for k, v in key:
            if k == label:
                vals.add(str(v))
    return sorted(vals)


def _hist_state(
    metric: Any, **match: str
) -> Optional[Tuple[float, float, Dict[str, float],
                    Optional[float], Optional[float]]]:
    """Merged cumulative state of a histogram's matching children as
    (count, sum, sparse buckets, min, max)."""
    want = set(match.items())
    count = total = 0.0
    bkt: Dict[str, float] = {}
    mn: Optional[float] = None
    mx: Optional[float] = None
    hit = False
    for key, val in metric.items():
        if not want.issubset(set(key)):
            continue
        hit = True
        c, s, lo, hi, buckets = val
        count += c
        total += s
        if c:
            mn = lo if mn is None else min(mn, lo)
            mx = hi if mx is None else max(mx, hi)
        for i, b in enumerate(buckets):
            if b:
                bkt[str(i)] = bkt.get(str(i), 0.0) + b
    return (count, total, bkt, mn, mx) if hit else None


class SignalPlane:
    """One per node (constructed by JobService): samples windows on
    every tick everywhere, but EVALUATES — burn monitors, health
    scores, alert transitions — only while this node leads. Registers
    the ALERT standby relay and the ALERT_PULL request/reply handlers
    (HANDLER_OWNERS owner: SignalPlane)."""

    #: queue-wait p95 slope (seconds of wait gained per second) that
    #: spends the trend budget at burn 1.0
    QWAIT_SLOPE_BUDGET = 0.05

    def __init__(
        self,
        node: Any,
        jobs: Any = None,
        clock: Callable[[], float] = time.monotonic,
        stride_s: Optional[float] = None,
    ):
        self.node = node
        self.jobs = jobs
        stride = (
            float(stride_s) if stride_s is not None
            else max(0.25, float(node.spec.timing.ping_interval))
        )
        self.windows = WindowSet(clock=clock, width_s=240 * stride,
                                 stride_s=stride)
        self.windows.publish()
        self.health = HealthScorer()
        self.alerts = AlertManager(clock=clock)
        self.alerts.on_transition.append(self._relay_transition)
        #: (signal, scope) -> monitor; created lazily on first
        #: evaluation so ``policy_factory`` overrides installed before
        #: traffic (benches, tests) shape every monitor
        self.monitors: Dict[Tuple[str, str], BurnRateMonitor] = {}
        self.policy_factory: Callable[[str, str], BurnRatePolicy] = (
            self._default_policy
        )
        self._node_hyst: Dict[str, Hysteresis] = {}
        self._liar_hyst: Dict[str, Hysteresis] = {}
        #: freshest bad-request exemplars pushed by the router at the
        #: shed / deadline-miss sites: kind -> recent (slo, trace_id)
        self._exemplars: Dict[str, Deque[Tuple[str, str]]] = {}
        self._tick_task: Optional[asyncio.Task] = None
        node.register(MsgType.ALERT, self._h_alert)
        node.register(MsgType.ALERT_PULL, self._h_alert_pull)

    @staticmethod
    def _default_policy(signal: str, scope: str) -> BurnRatePolicy:
        if signal == "shed_ratio":
            # shedding is the door doing its job; page only when it
            # is sustained and material
            return BurnRatePolicy(budget=2 * burn_budget(scope))
        return BurnRatePolicy(budget=burn_budget(scope))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._tick_task is None:
            self._tick_task = asyncio.create_task(
                self._tick_loop(),
                name=f"{self.node.me}-signal",
            )

    async def stop(self) -> None:
        t = self._tick_task
        self._tick_task = None
        await reap_task(t, self.node.me, "signal tick loop")

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(self.windows.stride_s)
            try:
                self.tick()
            except Exception:
                log.exception(
                    "%s: signal tick failed", self.node.me.unique_name
                )

    # -- observation intake --------------------------------------------

    def observe_ack(
        self, worker: str, observed_s: float, ack: Dict[str, Any]
    ) -> None:
        """Coordinator hook (JobService._h_task_ack): one worker batch
        ACK's two walls — the leader-observed dispatch→ACK wall and
        the worker's self-reported exec wall."""
        try:
            reported = float(ack.get("exec_time", 0.0))
            n = int(ack.get("n_images", 1))
        except (TypeError, ValueError):
            return
        self.health.observe_ack(worker, observed_s, reported, n)

    def note_bad_request(
        self, kind: str, slo: str, trace_id: Optional[str]
    ) -> None:
        """Router hook at the shed / deadline-miss sites: remember the
        freshest bad-request trace per kind+class so a firing alert
        can attach the exemplar that EXPLAINS it (not merely a recent
        one)."""
        if not trace_id:
            return
        self._exemplars.setdefault(
            kind, deque(maxlen=32)
        ).append((slo, trace_id))

    def _exemplar_for(self, kind: str, slo: str) -> Optional[str]:
        rows = self._exemplars.get(kind)
        if rows:
            for s, tid in reversed(rows):
                if s == slo:
                    return tid
            return rows[-1][1]
        # fall back to the flight recorder's pinned exemplar traces
        tids = TRACER.exemplar_trace_ids(kind=kind)
        return tids[-1] if tids else None

    # -- tick ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> float:
        """One signal-plane step: sample every window, then (leader
        only) evaluate monitors + health and drive the alert ledger.
        ``now`` is injectable for deterministic tests."""
        t = self._sample(now)
        _M_SIG_SAMPLES.inc()
        if self.node.is_leader:
            self._evaluate(t)
        return t

    def _sample(self, now: Optional[float] = None) -> float:
        ws = self.windows
        for cls in set(
            _label_values(_M_REQ_ADMITTED, "slo")
            + _label_values(_M_REQ_SHED, "slo")
        ):
            ws.track(
                f"miss:{cls}",
                lambda c=cls: _labeled_sum(_M_REQ_MISS, slo=c),
            )
            ws.track(
                f"completed:{cls}",
                lambda c=cls: _M_REQ_COMPLETED.value(slo=c),
            )
            ws.track(
                f"shed:{cls}",
                lambda c=cls: _labeled_sum(_M_REQ_SHED, slo=c),
            )
            ws.track(
                f"arrivals:{cls}",
                lambda c=cls: _M_REQ_ADMITTED.value(slo=c)
                + _labeled_sum(_M_REQ_SHED, slo=c),
            )
            ws.track_hist(
                f"qwait:{cls}", _M_REQ_QWAIT.edges,
                lambda c=cls: _hist_state(_M_REQ_QWAIT, slo=c),
            )
        for model in _label_values(_M_COORD_ACKS, "model"):
            ws.track(
                f"acks:{model}",
                lambda m=model: _M_COORD_ACKS.value(model=m),
            )
            if self.jobs is not None:
                ws.track(
                    f"queued:{model}",
                    lambda m=model: float(
                        self.jobs.scheduler.queue_depths().get(m, 0)
                    ),
                )
        t = ws.sample(now)
        # derived series: windowed queue-wait p95 per class, re-fed
        # into a scalar window so `trend` can report its slope
        for key in list(ws._hists):
            cls = key.split(":", 1)[1]
            p95 = ws._hists[key].quantile(0.95, t)
            if p95 is not None:
                ws.track(f"qwait_p95:{cls}", lambda: None).observe(t, p95)
        return t

    def _monitor(
        self, signal: str, scope: str, name: str,
        labels: Dict[str, Any],
    ) -> BurnRateMonitor:
        key = (signal, scope)
        m = self.monitors.get(key)
        if m is None:
            m = self.monitors[key] = BurnRateMonitor(
                self.policy_factory(signal, scope)
            )
            # a promoted leader inherits the dead leader's firing rows
            # via adopt(); its fresh monitors must start in the firing
            # state or the resolve transition could never happen
            m.hyst.firing = self.alerts.is_firing(name, labels)
        return m

    def _drive(
        self,
        trans: Optional[str],
        monitor: BurnRateMonitor,
        name: str,
        labels: Dict[str, Any],
        summary: str,
        exemplar: Optional[str],
        now: float,
    ) -> None:
        if trans is None:
            return
        sig = labels.get("signal", name)
        _M_SIG_TRANSITIONS.inc(signal=str(sig), to=trans)
        if trans == "fire":
            burn = max(
                monitor.last.get("burn_short", 0.0),
                monitor.last.get("burn_long", 0.0),
            )
            sev = "critical" if burn >= 2 * monitor.policy.fire_burn \
                else "warning"
            self.fire_alert(
                name, labels, severity=sev, summary=summary,
                exemplar=exemplar, now=now,
            )
        else:
            self.resolve_alert(name, labels, now=now)

    def _evaluate(self, now: float) -> None:
        ws = self.windows
        classes = sorted({
            k.split(":", 1)[1] for k in ws._windows if k.startswith("miss:")
        })
        for cls in classes:
            miss = ws.window(f"miss:{cls}")
            done = ws.window(f"completed:{cls}")
            shed = ws.window(f"shed:{cls}")
            arrivals = ws.window(f"arrivals:{cls}")
            if miss is not None and done is not None:
                labels = {"slo": cls, "signal": "deadline_miss"}
                m = self._monitor(
                    "deadline_miss", cls, "slo_burn_rate", labels
                )
                self._drive(
                    m.evaluate(now, miss, done), m,
                    "slo_burn_rate", labels,
                    f"{cls}: deadline-miss burn "
                    f"{m.last.get('burn_short')}x/{m.last.get('burn_long')}x "
                    f"of budget",
                    self._exemplar_for("deadline_miss", cls), now,
                )
            if shed is not None and arrivals is not None:
                labels = {"slo": cls, "signal": "shed_ratio"}
                m = self._monitor(
                    "shed_ratio", cls, "slo_burn_rate", labels
                )
                self._drive(
                    m.evaluate(now, shed, arrivals), m,
                    "slo_burn_rate", labels,
                    f"{cls}: shed-ratio burn "
                    f"{m.last.get('burn_short')}x/{m.last.get('burn_long')}x "
                    f"of budget",
                    self._exemplar_for("shed", cls), now,
                )
            p95w = ws.window(f"qwait_p95:{cls}")
            if p95w is not None:
                labels = {"slo": cls, "signal": "queue_wait_trend"}
                m = self._monitor(
                    "queue_wait_trend", cls, "slo_burn_rate", labels
                )
                p = m.policy
                bs = p95w.trend(now, p.short_s) / self.QWAIT_SLOPE_BUDGET
                bl = p95w.trend(now, p.long_s) / self.QWAIT_SLOPE_BUDGET
                self._drive(
                    m.evaluate_burns(now, bs, bl), m,
                    "slo_burn_rate", labels,
                    f"{cls}: queue-wait p95 rising "
                    f"{m.last.get('burn_short')}x/{m.last.get('burn_long')}x "
                    f"of trend budget",
                    self._exemplar_for("deadline_miss", cls), now,
                )
        # per model: the queue has work but ACK flow stalled
        models = sorted({
            k.split(":", 1)[1] for k in ws._windows if k.startswith("acks:")
        })
        for model in models:
            acks = ws.window(f"acks:{model}")
            queued = ws.window(f"queued:{model}")
            if acks is None or queued is None:
                continue
            labels = {"model": model, "signal": "model_stall"}
            m = self._monitor("model_stall", model, "slo_burn_rate", labels)
            p = m.policy
            burns = []
            for w in (p.short_s, p.long_s):
                pts = queued._span(now, w)
                starving = (
                    len(pts) >= 2
                    and all(v > 0 for _, v in pts)
                    and acks.delta(now, w) <= 0
                )
                burns.append(2.0 * p.fire_burn if starving else 0.0)
            self._drive(
                m.evaluate_burns(now, burns[0], burns[1]), m,
                "slo_burn_rate", labels,
                f"{model}: queued work with no ACK flow",
                None, now,
            )
        self._evaluate_health(now)

    def _evaluate_health(self, now: float) -> None:
        zs = self.health.zscores()
        for worker, z in zs.items():
            h = self._node_hyst.setdefault(worker, Hysteresis(2, 3))
            h.firing = h.firing or self.alerts.is_firing(
                "node_unhealthy", {"node": worker}
            )
            trans = h.update(
                True if z >= self.health.z_fire
                else (False if z <= self.health.z_fire / 2 else None)
            )
            if trans == "fire":
                _M_SIG_TRANSITIONS.inc(signal="node_z", to="fire")
                self.fire_alert(
                    "node_unhealthy", {"node": worker},
                    severity="warning",
                    summary=f"{worker}: stage walls z={z:.1f} vs pool",
                    now=now,
                )
            elif trans == "resolve":
                _M_SIG_TRANSITIONS.inc(signal="node_z", to="resolve")
                self.resolve_alert(
                    "node_unhealthy", {"node": worker}, now=now
                )
        for worker in list(self.health._pairs):
            ev = self.health.crosscheck(worker)
            h = self._liar_hyst.setdefault(worker, Hysteresis(1, 8))
            h.firing = h.firing or self.alerts.is_firing(
                "metrics_liar", {"node": worker}
            )
            trans = h.update(ev is not None)
            if trans == "fire":
                _M_SIG_LIAR.inc()
                _M_SIG_TRANSITIONS.inc(signal="crosscheck", to="fire")
                self.fire_alert(
                    "metrics_liar", {"node": worker},
                    severity="critical",
                    summary=(
                        f"{worker}: observed wall "
                        f"{ev['observed_s']}s vs self-reported "
                        f"{ev['reported_s']}s over {ev['samples']} ACKs"
                    ),
                    now=now,
                )
            elif trans == "resolve":
                _M_SIG_TRANSITIONS.inc(signal="crosscheck", to="resolve")
                self.resolve_alert(
                    "metrics_liar", {"node": worker}, now=now
                )

    # convenience pass-throughs so emission sites stay on the plane
    # (and the lint rule sees one call-shape everywhere)
    def fire_alert(self, name: str, labels=None, **kw: Any) -> bool:
        return self.alerts.fire_alert(name, labels, **kw)

    def resolve_alert(self, name: str, labels=None, **kw: Any) -> bool:
        return self.alerts.resolve_alert(name, labels, **kw)

    # -- wire surface --------------------------------------------------

    def _relay_transition(
        self, event: Dict[str, Any], row: Dict[str, Any]
    ) -> None:
        """Every ledger transition rides one small datagram to the hot
        standby, so a promoted leader inherits the firing set (the
        INGRESS_RELAY / STORE_IDEMPOTENCY_RELAY discipline applied to
        alerts)."""
        if not self.node.is_leader:
            return
        sb = self.node.standby_node()
        if sb is None or sb.unique_name == self.node.me.unique_name:
            return
        try:
            self.node.send(
                sb, MsgType.ALERT, {"row": row, "event": event["event"]}
            )
            _M_ALERT_RELAYS.inc()
        except ValueError:
            # a single row over the frame cap would need a ~60 KB
            # label set; drop rather than kill the transition path
            log.warning("alert relay row over the datagram cap")

    async def _h_alert(self, msg: Message, addr) -> None:
        """Standby side of the ledger relay: adopt the row. Only the
        CURRENT leader's ledger is authoritative — a stale ex-leader's
        late datagram must not resurrect resolved alerts."""
        if msg.sender != self.node.leader_unique:
            return
        row = msg.data.get("row")
        if isinstance(row, dict):
            if self.alerts.adopt([row]):
                log.debug(
                    "%s: adopted relayed alert %s (%s)",
                    self.node.me.unique_name, row.get("name"),
                    msg.data.get("event"),
                )

    async def _h_alert_pull(self, msg: Message, addr) -> None:
        """ALERT_PULL is request/reply on ONE MsgType: a reply leg
        carries our own rid and resolves the awaiting future here
        (the DOWNLOAD_FILE_SUCCESS discipline); a request leg gets
        the ledger + recent events + health rollup, degrading tier by
        tier through the shared cap machinery."""
        if self.node.resolve_rid(msg):
            return
        if self.node.spec.node_by_unique_name(msg.sender) is None:
            return  # forged out-of-universe datagram
        d = msg.data
        try:
            max_events = int(d.get("max_events", 256))
        except (TypeError, ValueError):
            return
        max_events = min(max(max_events, 1), 2048)
        rows = self.alerts.rows()
        events = self.alerts.stream()[-max_events:]
        health = self.health_summary()
        extra = {
            "rid": d.get("rid"),
            "ok": True,
            "node": self.node.me.unique_name,
        }
        self.node.send_tiered(
            msg.sender, MsgType.ALERT_PULL, extra,
            tiers=(
                lambda: {"alerts": rows, "events": events,
                         "health": health},
                lambda: {"alerts": rows, "events": events[-32:],
                         "health": health, "truncated": "events"},
                lambda: {"alerts": rows[-32:], "events": [],
                         "health": {}, "truncated": "events,health"},
            ),
            what="alert ledger",
        )

    def autoscale_snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Controller-facing digest of the plane's current verdicts
        (dml_tpu/autoscale.py consumes one per evaluation tick):
        firing burn monitors, the SLO classes they convict, stalled
        models, per-model queue backlog, per-class arrival rates, and
        the liar/unhealthy node sets. Everything is sorted + rounded
        so a recorded tick schedule is JSON-able and replays through
        ``autoscale.replay_decision_stream`` byte-identically."""
        ws = self.windows
        t = ws.now() if now is None else float(now)
        burn: List[str] = []
        culprits: set = set()
        stalled: List[str] = []
        for (sig, scope), m in sorted(self.monitors.items()):
            if not m.hyst.firing:
                continue
            burn.append(f"{sig}|{scope}")
            if sig == "model_stall":
                stalled.append(scope)
            else:
                culprits.add(scope)
        backlog: Dict[str, float] = {}
        arrivals: Dict[str, float] = {}
        for key, w in sorted(ws._windows.items()):
            if key.startswith("queued:"):
                v = w.last()
                if v:
                    backlog[key.split(":", 1)[1]] = round(float(v), 2)
            elif key.startswith("arrivals:"):
                # lookback rides the window geometry (10 strides): the
                # idleness verdict must clear within a few evaluation
                # ticks of traffic actually stopping, at bench and
                # product timescales alike
                arrivals[key.split(":", 1)[1]] = round(
                    w.rate(t, 10.0 * ws.stride_s), 4
                )
        liars = set(self.health.liars())
        unhealthy: set = set()
        for row in self.alerts.active():
            if row["name"] == "metrics_liar":
                liars.add(str(row["labels"].get("node")))
            elif row["name"] == "node_unhealthy":
                unhealthy.add(str(row["labels"].get("node")))
        return {
            "t": round(t, 3),
            "burn_firing": burn,
            "culprit_classes": sorted(culprits),
            "stalled_models": sorted(stalled),
            "backlog": backlog,
            "arrivals_qps": arrivals,
            "liars": sorted(liars),
            "unhealthy": sorted(unhealthy),
        }

    def health_summary(self) -> Dict[str, Any]:
        """The CLI ``health`` verb's payload: per-node scores plus the
        latest burn evaluation per monitor scope."""
        return {
            "nodes": self.health.scores(),
            "monitors": {
                f"{sig}:{scope}": dict(m.last)
                for (sig, scope), m in sorted(self.monitors.items())
                if m.last
            },
            "firing": len(self.alerts.active()),
        }


# ----------------------------------------------------------------------
# deterministic replay
# ----------------------------------------------------------------------

def replay_alert_stream(
    ticks: Sequence[Dict[str, Dict[str, Any]]],
    policy: Optional[BurnRatePolicy] = None,
    clock0: float = 0.0,
    stride_s: float = 1.0,
) -> List[Dict[str, Any]]:
    """Drive a recorded observation schedule through FRESH windows,
    monitors, and an AlertManager under an injected clock.

    Each tick maps scope -> {"bad": cumulative, "total": cumulative,
    "exemplar"?: trace_id}. Pure function of its inputs: the same
    ticks and policy produce a byte-identical event stream (compare
    ``json.dumps(..., sort_keys=True)`` of the return), which is how
    the bench proves seed-determinism without pretending live cluster
    walls are reproducible."""
    pol = policy or BurnRatePolicy()
    width = max(pol.long_s * 2, stride_s * 4)
    windows: Dict[str, Tuple[MetricWindow, MetricWindow]] = {}
    monitors: Dict[str, BurnRateMonitor] = {}
    t = clock0
    mgr = AlertManager(clock=lambda: t)
    for i, tick in enumerate(ticks):
        t = clock0 + i * stride_s
        for scope, obs in sorted(tick.items()):
            bw, tw = windows.setdefault(scope, (
                MetricWindow(width_s=width, stride_s=stride_s),
                MetricWindow(width_s=width, stride_s=stride_s),
            ))
            bw.observe(t, float(obs.get("bad", 0.0)))
            tw.observe(t, float(obs.get("total", 0.0)))
            m = monitors.setdefault(scope, BurnRateMonitor(pol))
            trans = m.evaluate(t, bw, tw)
            if trans == "fire":
                mgr.fire_alert(
                    "slo_burn_rate", {"slo": scope},
                    summary=f"{scope}: replayed burn breach",
                    exemplar=obs.get("exemplar"), now=t,
                )
            elif trans == "resolve":
                mgr.resolve_alert("slo_burn_rate", {"slo": scope}, now=t)
    return mgr.stream()
