"""Closed-loop autoscaler: SLO-burn-driven scale-out/in and capacity
reallocation (ROADMAP item 2, the loop the signal plane was built
for).

The signal plane (signal.py) turns the cluster's raw counters into
typed verdicts — burn-rate alerts, queue backlog, liar convictions —
but nothing acts on them: a saturated cluster pages and keeps burning.
This module closes the loop with a leader-resident, deterministic
controller in three pieces:

- ``AutoscalePolicy``: the knobs — pool floor/ceiling, pressure and
  idleness thresholds, per-kind cooldowns, hysteresis depths, the
  scale-in confirm window, reallocation step/cap.
- ``DecisionLedger``: the controller's memory — an append-only typed
  decision stream (``propose`` → ``apply``/``cancel``) with the same
  byte-identical ``stream_json()`` replay discipline as
  ``AlertManager``, plus the per-kind cooldown ledger. Every event is
  relayed to the hot standby (``MsgType.AUTOSCALE``) so a promoted
  leader inherits cooldowns and in-flight decisions and settles each
  decision id EXACTLY ONCE across the failover.
- ``AutoscaleController``: reads one ``SignalPlane.autoscale_snapshot``
  per tick through an injected clock and drives three decision kinds
  over the elastic-membership machinery:

  * **scale-out** — sustained pressure (firing burn alerts, or queue
    backlog beyond ``backlog_per_slot`` per schedulable slot) admits
    standby capacity via the environment's ``scale_out_fn`` (runtime
    JOIN, chaos/bench wire it to ``LocalCluster.scale_out``). While a
    ``metrics_liar`` conviction is live, scale-out pressure is MASKED:
    a forged-evidence straggler manufactures backlog, and paying for
    chips is not the cure for a liar.
  * **scale-in** — sustained idleness retires the newest idle slot by
    graceful LEAVE, never a node convicted unhealthy/liar, never one
    holding in-flight batches, never below ``floor``. Proposals hold
    for ``confirm_ticks`` evaluations and are CANCELLED (typed
    ``cancel``, reason ``spike``) if pressure returns first — the
    scale-in-racing-a-spike chaos case.
  * **reallocation** — when the plane's burn attribution names exactly
    one SLO class as the culprit, its ``Scheduler.class_weights``
    share is stepped up (capped), applied immediately and carried in
    the decision row so a promoted leader re-applies the same split.

Determinism contract: ``step()`` is a pure function of the snapshot
dicts and the controller's own state — ``replay_decision_stream``
drives a recorded tick schedule through a fresh controller and the
bench compares ``stream_json()`` bytes across two replays.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import (
    Any, Awaitable, Callable, Deque, Dict, List, Optional, Sequence,
    Set, Tuple,
)

from .cluster.util import reap_task
from .cluster.wire import Message, MsgType
from .observability import METRICS
from .signal import Hysteresis

log = logging.getLogger(__name__)

#: the closed decision taxonomy; every ledger row carries one of these
DECISION_KINDS = ("scale_out", "scale_in", "reallocate")

_M_AS_DECISIONS = METRICS.counter(
    "autoscale_decisions_total",
    "controller decision-stream events, per kind= event=")
_M_AS_POOL = METRICS.gauge(
    "autoscale_pool_size",
    "schedulable worker slots the controller last observed")
_M_AS_RELAYS = METRICS.counter(
    "autoscale_relays_total",
    "decision-ledger events relayed leader -> standby")
_M_AS_SUPPRESSED = METRICS.counter(
    "autoscale_suppressed_total",
    "decisions suppressed by a policy guard, per reason=")


@dataclass(frozen=True)
class AutoscalePolicy:
    """The controller's knobs.

    ``floor``/``ceiling``  hard pool bounds: scale-in never proposes
                    below ``floor`` (counting its own un-settled
                    proposals), scale-out never above ``ceiling``.
    ``backlog_per_slot``  queued batches per schedulable slot that
                    count as pressure even before a burn alert fires
                    (the coordinator-side signal: job bursts without
                    ingress traffic still saturate the pool).
    ``idle_arrival_qps``  arrival rate at/below which a drained pool
                    reads as idle.
    ``out_*``/``in_*``  hysteresis depths per direction — scale-in
                    demands a longer streak than scale-out because
                    shedding capacity is the riskier mistake — plus
                    per-kind cooldowns debouncing repeat decisions.
    ``confirm_ticks``  evaluations a scale-in proposal holds before
                    actuating; pressure returning inside the window
                    cancels it (typed ``cancel``, reason ``spike``).
    ``realloc_step``/``realloc_cap``  multiplicative class-weight step
                    for the culprit class and its absolute cap.
    ``apply_timeout_s``  a proposed decision whose effect never lands
                    (join refused, leaver wedged) is cancelled instead
                    of pinning its kind's in-flight slot forever.
    """

    floor: int = 2
    ceiling: int = 8
    backlog_per_slot: float = 8.0
    idle_arrival_qps: float = 0.05
    out_fire_after: int = 2
    out_clear_after: int = 2
    in_fire_after: int = 4
    in_clear_after: int = 1
    confirm_ticks: int = 1
    out_cooldown_s: float = 10.0
    in_cooldown_s: float = 20.0
    realloc_cooldown_s: float = 30.0
    realloc_step: float = 0.5
    realloc_cap: float = 8.0
    apply_timeout_s: float = 30.0


class DecisionLedger:
    """Append-only autoscale decision stream + cooldown ledger.

    Rows move ``proposed`` → ``applied`` | ``cancelled`` exactly once
    (``settle`` on a non-proposed row is a no-op — idempotent across
    relays and failovers, the exactly-once actuation surface the chaos
    sweep asserts on). The event stream carries one typed event per
    transition and serializes byte-identically under an injected clock
    (``AlertManager.stream_json`` discipline); ``adopt`` merges relayed
    rows + cooldowns so a promoted leader inherits both."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_rows: int = 256,
        max_events: int = 1024,
    ):
        self._clock = clock
        self.max_rows = int(max_rows)
        self._rows: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._events: Deque[Dict[str, Any]] = deque(maxlen=int(max_events))
        self._seq = 0
        #: kind -> not-before time (injected-clock domain)
        self.cooldowns: Dict[str, float] = {}
        #: transition observers, called as cb(event, row); must not
        #: raise (guarded) — the controller's standby relay rides this
        self.on_event: List[
            Callable[[Dict[str, Any], Dict[str, Any]], None]
        ] = []

    def _now(self, now: Optional[float]) -> float:
        return self._clock() if now is None else float(now)

    def in_cooldown(self, kind: str, now: Optional[float] = None) -> bool:
        return self._now(now) < self.cooldowns.get(kind, float("-inf"))

    def arm_cooldown(self, kind: str, until: float) -> None:
        self.cooldowns[kind] = round(float(until), 3)

    def _emit(self, event: Dict[str, Any], row: Dict[str, Any]) -> None:
        self._events.append(event)
        for cb in list(self.on_event):
            try:
                cb(event, row)
            except Exception:
                log.exception("decision event observer failed")

    def _bound(self) -> None:
        while len(self._rows) > self.max_rows:
            victim = next(
                (k for k, r in self._rows.items()
                 if r["state"] != "proposed"),
                next(iter(self._rows)),
            )
            del self._rows[victim]

    def propose(
        self,
        kind: str,
        target: Optional[str] = None,
        *,
        reason: str = "",
        detail: Optional[Dict[str, Any]] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Open a decision. The id embeds the ledger seq, so a decision
        minted by the dead leader and one minted by its successor can
        never collide (the successor's seq continues past every adopted
        row's)."""
        if kind not in DECISION_KINDS:
            raise ValueError(f"unknown decision kind {kind!r}")
        t = round(self._now(now), 3)
        self._seq += 1
        did = f"{kind}:{target or '-'}:{self._seq}"
        row = {
            "id": did,
            "kind": kind,
            "target": target,
            "state": "proposed",
            "reason": reason,
            "since": t,
            "last": t,
            "seq": self._seq,
            "detail": dict(detail or {}),
        }
        self._rows[did] = row
        self._rows.move_to_end(did)
        self._bound()
        _M_AS_DECISIONS.inc(kind=kind, event="propose")
        self._emit(
            {"seq": self._seq, "t": t, "event": "propose", "id": did,
             "kind": kind, "target": target, "reason": reason},
            row,
        )
        return row

    def settle(
        self,
        did: str,
        outcome: str,
        *,
        reason: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Close a proposed decision as ``applied`` or ``cancelled``.
        Returns True on the transition; settling an unknown or already-
        settled row is a no-op — the exactly-once guarantee a promoted
        leader leans on after adopting the dead leader's ledger."""
        if outcome not in ("applied", "cancelled"):
            raise ValueError(f"unknown decision outcome {outcome!r}")
        row = self._rows.get(did)
        if row is None or row["state"] != "proposed":
            return False
        t = round(self._now(now), 3)
        self._seq += 1
        row["state"] = outcome
        row["last"] = t
        row["seq"] = self._seq
        if reason:
            row["reason"] = reason
        ev = "apply" if outcome == "applied" else "cancel"
        _M_AS_DECISIONS.inc(kind=row["kind"], event=ev)
        self._emit(
            {"seq": self._seq, "t": t, "event": ev, "id": did,
             "kind": row["kind"], "target": row["target"],
             "reason": reason},
            row,
        )
        return True

    def mark_actuated(
        self, did: str, *, now: Optional[float] = None
    ) -> bool:
        """Record that a proposed decision's actuator FIRED (the LEAVE
        was sent, the join was requested) before its effect is
        observable in the universe. A typed ``actuate`` event hits the
        stream — and therefore the standby relay — so a leader killed
        between firing and the actuation ACK leaves a successor that
        knows not to fire again, and the merged per-node streams
        expose exactly-once actuation directly."""
        row = self._rows.get(did)
        if (
            row is None
            or row["state"] != "proposed"
            or row["detail"].get("actuated")
        ):
            return False
        t = round(self._now(now), 3)
        self._seq += 1
        row["detail"]["actuated"] = True
        row["last"] = t
        row["seq"] = self._seq
        _M_AS_DECISIONS.inc(kind=row["kind"], event="actuate")
        self._emit(
            {"seq": self._seq, "t": t, "event": "actuate", "id": did,
             "kind": row["kind"], "target": row["target"], "reason": ""},
            row,
        )
        return True

    def pending(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        return sorted(
            (r for r in self._rows.values()
             if r["state"] == "proposed"
             and (kind is None or r["kind"] == kind)),
            key=lambda r: r["seq"],
        )

    def rows(self) -> List[Dict[str, Any]]:
        return sorted(
            (dict(r) for r in self._rows.values()),
            key=lambda r: r["seq"],
        )

    def stream(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def stream_json(self) -> bytes:
        """Canonical serialization of the decision stream — the byte-
        identical determinism surface the bench compares."""
        return json.dumps(
            self.stream(), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")

    def adopt(
        self,
        rows: Sequence[Dict[str, Any]],
        cooldowns: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Merge relayed rows + cooldowns (standby side of the
        AUTOSCALE relay; also the promoted leader's inheritance path).
        Newest-wins by the row's ``last`` stamp; cooldowns merge by
        max, so the successor can only be MORE debounced than the dead
        leader, never less. Malformed rows are dropped, not raised —
        the relay rides fire-and-forget datagrams."""
        n = 0
        for row in rows:
            if not isinstance(row, dict):
                continue
            did = row.get("id")
            if not isinstance(did, str):
                continue
            if row.get("kind") not in DECISION_KINDS:
                continue
            if row.get("state") not in ("proposed", "applied", "cancelled"):
                continue
            cur = self._rows.get(did)
            if cur is not None and cur.get("last", 0) >= row.get("last", 0):
                continue
            adopted = dict(row)
            adopted["detail"] = dict(row.get("detail") or {})
            self._seq = max(self._seq, int(adopted.get("seq", 0)))
            self._rows[did] = adopted
            self._rows.move_to_end(did)
            n += 1
        if n:
            self._bound()
        for kind, until in (cooldowns or {}).items():
            if kind in DECISION_KINDS:
                try:
                    u = float(until)
                except (TypeError, ValueError):
                    continue
                if u > self.cooldowns.get(kind, float("-inf")):
                    self.cooldowns[kind] = u
        return n


class AutoscaleController:
    """One per node (constructed by JobService next to the
    SignalPlane): adopts relayed ledger state everywhere, but
    EVALUATES — and actuates — only while this node leads. Registers
    the AUTOSCALE standby relay handler (HANDLER_OWNERS owner:
    AutoscaleController).

    Actuation is environment-provided: ``scale_out_fn`` /
    ``scale_in_fn`` are injected by whatever owns real capacity (the
    chaos harness and bench wire ``LocalCluster.scale_out`` /
    ``scale_in``; a bare controller emits decisions only), while
    reallocation applies directly to the scheduler. A ``node=None``
    controller is the pure policy core ``replay_decision_stream``
    drives."""

    def __init__(
        self,
        node: Any = None,
        jobs: Any = None,
        plane: Any = None,
        policy: Optional[AutoscalePolicy] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.node = node
        self.jobs = jobs
        self.plane = plane
        self.policy = policy or AutoscalePolicy()
        self._clock = clock
        self.ledger = DecisionLedger(clock=clock)
        self.ledger.on_event.append(self._relay_event)
        self._out_hyst = Hysteresis(
            self.policy.out_fire_after, self.policy.out_clear_after
        )
        self._in_hyst = Hysteresis(
            self.policy.in_fire_after, self.policy.in_clear_after
        )
        #: environment actuators (None = decision-only mode)
        self.scale_out_fn: Optional[Callable[[], Awaitable[Any]]] = None
        self.scale_in_fn: Optional[Callable[[str], Awaitable[Any]]] = None
        #: smallest pool the controller ever evaluated — the invariant
        #: sweep's pool-never-below-floor witness
        self.min_pool_seen: Optional[int] = None
        self._eval_task: Optional[asyncio.Task] = None
        self._bg: Set[asyncio.Task] = set()
        if node is not None:
            node.register(MsgType.AUTOSCALE, self._h_autoscale)
            node.on_became_leader_cbs.append(self._on_promoted)
            node.on_node_left_cbs.append(self._on_node_left)

    def configure(self, policy: AutoscalePolicy) -> None:
        """Swap the policy in place (harnesses wire this after the
        JobService constructed the controller). Hysteresis depths
        rebuild from the new policy; call before traffic, not
        mid-flight, or streak state resets under the controller."""
        self.policy = policy
        self._out_hyst = Hysteresis(
            policy.out_fire_after, policy.out_clear_after
        )
        self._in_hyst = Hysteresis(
            policy.in_fire_after, policy.in_clear_after
        )

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._eval_task is None and self.plane is not None:
            self._eval_task = asyncio.create_task(
                self._eval_loop(),
                name=f"{self.node.me}-autoscale",
            )

    async def stop(self) -> None:
        t = self._eval_task
        self._eval_task = None
        await reap_task(t, self.node.me if self.node else "-", "autoscale loop")
        for bg in list(self._bg):
            bg.cancel()
        self._bg.clear()

    async def _eval_loop(self) -> None:
        while True:
            await asyncio.sleep(self.plane.windows.stride_s)
            try:
                self.evaluate()
            except Exception:
                log.exception(
                    "%s: autoscale evaluation failed",
                    self.node.me.unique_name,
                )

    # -- evaluation ----------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One live control step (leader only): snapshot the signal
        plane + scheduler, run the deterministic core, then fire the
        environment actuators for whatever the core decided. Returns
        the decision events this step emitted."""
        if self.node is None or not self.node.is_leader:
            return []
        t = self._clock() if now is None else float(now)
        snap = self.plane.autoscale_snapshot(t)
        snap["pool"] = sorted(self.jobs.worker_pool())
        snap["busy"] = sorted(
            set(self.jobs.scheduler.in_progress)
            | set(self.jobs.scheduler.prefetch)
        )
        snap["class_weights"] = {
            k: round(float(v), 4)
            for k, v in sorted(self.jobs.scheduler.class_weights.items())
        }
        before = len(self.ledger.stream())
        acts = self.step(snap)
        for kind, target in acts:
            self._actuate(kind, target)
        return self.ledger.stream()[before:]

    def step(
        self, snap: Dict[str, Any]
    ) -> List[Tuple[str, Optional[str]]]:
        """The deterministic policy core: one snapshot in, ledger
        transitions + an actuation list out. Pure function of the
        snapshot sequence and the controller's own state — no wall
        clock, no registry reads — so a recorded tick schedule replays
        byte-identically (``replay_decision_stream``)."""
        p = self.policy
        t = float(snap["t"])
        pool = list(snap.get("pool") or [])
        n = len(pool)
        self.min_pool_seen = (
            n if self.min_pool_seen is None else min(self.min_pool_seen, n)
        )
        _M_AS_POOL.set(n)
        acts: List[Tuple[str, Optional[str]]] = []

        # settle in-flight decisions against the observed pool: a
        # scale-out applies when capacity actually joined, a scale-in
        # when the target actually left — the actuation ACK is the
        # universe itself, so a promoted leader settles an inherited
        # decision from observation instead of trusting relay order
        for row in self.ledger.pending():
            if row["kind"] == "scale_out":
                if n > int(row["detail"].get("pool_n", 0)):
                    self.ledger.settle(
                        row["id"], "applied",
                        reason="capacity-joined", now=t,
                    )
                elif t - row["since"] > p.apply_timeout_s:
                    self.ledger.settle(
                        row["id"], "cancelled", reason="timeout", now=t,
                    )
            elif row["kind"] == "scale_in":
                if row["target"] not in pool:
                    self.ledger.settle(
                        row["id"], "applied",
                        reason="leave-observed", now=t,
                    )
                elif t - row["since"] > p.apply_timeout_s:
                    self.ledger.settle(
                        row["id"], "cancelled", reason="timeout", now=t,
                    )

        backlog = sum(
            float(v) for v in (snap.get("backlog") or {}).values()
        )
        arrivals = sum(
            float(v) for v in (snap.get("arrivals_qps") or {}).values()
        )
        liars = set(snap.get("liars") or [])
        unhealthy = set(snap.get("unhealthy") or [])
        burn = list(snap.get("burn_firing") or [])
        pressure = bool(burn) or backlog > p.backlog_per_slot * max(1, n)
        idle = (
            not pressure
            and backlog <= 0
            and arrivals <= p.idle_arrival_qps
        )
        if pressure and liars:
            # a convicted liar's stall manufactures backlog and burn;
            # admitting capacity would pay for forged evidence, so the
            # pressure streak HOLDS (None) instead of advancing
            self._out_hyst.update(None)
            _M_AS_SUPPRESSED.inc(reason="liar")
        else:
            self._out_hyst.update(True if pressure else False)
        self._in_hyst.update(
            True if idle else (False if pressure else None)
        )

        pending_out = len(self.ledger.pending("scale_out"))
        pending_in = len(self.ledger.pending("scale_in"))

        # scale-out: debounced pressure admits one slot per cooldown
        if self._out_hyst.firing and pressure and not liars:
            if n + pending_out >= p.ceiling:
                _M_AS_SUPPRESSED.inc(reason="ceiling")
            elif self.ledger.in_cooldown("scale_out", t):
                _M_AS_SUPPRESSED.inc(reason="cooldown")
            elif pending_out == 0:
                self.ledger.propose(
                    "scale_out", None,
                    reason="slo-burn" if burn else "backlog",
                    detail={
                        "pool_n": n,
                        "burn": burn[:4],
                        "backlog": round(backlog, 2),
                    },
                    now=t,
                )
                self.ledger.arm_cooldown("scale_out", t + p.out_cooldown_s)
                acts.append(("scale_out", None))

        # scale-in: pending proposals ride the confirm window; a spike
        # arriving inside it cancels rather than races the LEAVE. A row
        # whose actuator already fired is past cancelling — the LEAVE
        # is in flight and the pool shrink itself re-arms the pressure
        # path, which is the compensation
        for row in self.ledger.pending("scale_in"):
            if row["detail"].get("actuated"):
                continue
            if pressure:
                self.ledger.settle(
                    row["id"], "cancelled", reason="spike", now=t,
                )
            else:
                left = int(row["detail"].get("confirm_left", 0))
                if left > 0:
                    row["detail"]["confirm_left"] = left - 1
                elif self.ledger.mark_actuated(row["id"], now=t):
                    acts.append(("scale_in", row["target"]))
        if self._in_hyst.firing and idle:
            if n - pending_in <= p.floor:
                _M_AS_SUPPRESSED.inc(reason="floor")
            elif self.ledger.in_cooldown("scale_in", t):
                _M_AS_SUPPRESSED.inc(reason="cooldown")
            elif pending_in == 0:
                victim = self._victim(snap, pool, liars | unhealthy)
                if victim is not None:
                    self.ledger.propose(
                        "scale_in", victim, reason="idle",
                        detail={
                            "pool_n": n,
                            "confirm_left": p.confirm_ticks,
                        },
                        now=t,
                    )
                    self.ledger.arm_cooldown(
                        "scale_in", t + p.in_cooldown_s
                    )

        # reallocation: exactly one SLO class named as the burn
        # culprit while others are healthy -> step its fair share up
        culprits = list(snap.get("culprit_classes") or [])
        weights = {
            k: float(v)
            for k, v in (snap.get("class_weights") or {}).items()
        }
        if (
            len(culprits) == 1
            and len(weights) >= 2
            and culprits[0] in weights
        ):
            if self.ledger.in_cooldown("reallocate", t):
                _M_AS_SUPPRESSED.inc(reason="cooldown")
            else:
                cls = culprits[0]
                new = {k: round(v, 4) for k, v in weights.items()}
                new[cls] = round(
                    min(weights[cls] * (1.0 + p.realloc_step),
                        p.realloc_cap),
                    4,
                )
                if new != {k: round(v, 4) for k, v in weights.items()}:
                    row = self.ledger.propose(
                        "reallocate", cls, reason="p99-culprit",
                        detail={"weights": new, "prev": {
                            k: round(v, 4) for k, v in weights.items()
                        }},
                        now=t,
                    )
                    # weight surgery is local + instant: applied in
                    # the same step, no external ACK to wait on
                    self.ledger.settle(
                        row["id"], "applied", reason="weights-set", now=t,
                    )
                    self.ledger.arm_cooldown(
                        "reallocate", t + p.realloc_cooldown_s
                    )
                    acts.append(("reallocate", cls))
        return acts

    @staticmethod
    def _victim(
        snap: Dict[str, Any], pool: List[str], convicted: Set[str]
    ) -> Optional[str]:
        """Deterministic scale-in victim: never a convicted node,
        never one holding in-flight/staged batches; among the eligible,
        the newest capacity goes first — runtime joiners get the
        highest ports, and the (len, str) key orders ``host:port``
        unames numerically by port."""
        busy = set(snap.get("busy") or [])
        elig = [u for u in pool if u not in convicted and u not in busy]
        if not elig:
            return None
        return max(elig, key=lambda u: (len(u), u))

    def _actuate(self, kind: str, target: Optional[str]) -> None:
        if kind == "reallocate":
            if self.jobs is not None:
                rows = [
                    r for r in self.ledger.rows()
                    if r["kind"] == "reallocate" and r["state"] == "applied"
                ]
                if rows:
                    w = rows[-1]["detail"].get("weights")
                    if isinstance(w, dict):
                        self.jobs.scheduler.reweight_classes(
                            {k: float(v) for k, v in w.items()}
                        )
            return
        fn: Optional[Callable[..., Awaitable[Any]]] = None
        args: Tuple[Any, ...] = ()
        if kind == "scale_out" and self.scale_out_fn is not None:
            fn = self.scale_out_fn
        elif kind == "scale_in" and self.scale_in_fn is not None:
            fn = self.scale_in_fn
            args = (target,)
        if fn is None:
            return
        try:
            task = asyncio.get_running_loop().create_task(fn(*args))
        except RuntimeError:
            log.debug("no running loop; %s actuation skipped", kind)
            return
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    # -- failover inheritance ------------------------------------------

    def _on_promoted(self) -> None:
        """Promotion hook: the adopted ledger already carries the dead
        leader's cooldowns and in-flight decisions (settled exactly
        once by observation in the next ``step``); the one piece that
        needs re-actuation is the class-weight split, which lives in
        the scheduler the dead leader mutated, not ours."""
        if self.jobs is None:
            return
        rows = [
            r for r in self.ledger.rows()
            if r["kind"] == "reallocate" and r["state"] == "applied"
        ]
        if rows:
            w = rows[-1]["detail"].get("weights")
            if isinstance(w, dict):
                try:
                    self.jobs.scheduler.reweight_classes(
                        {k: float(v) for k, v in w.items()}
                    )
                except (TypeError, ValueError):
                    log.warning("adopted reallocation row malformed")

    def _on_node_left(self, uname: str) -> None:
        """Graceful-LEAVE observation (fires on every node applying
        the universe removal): the leader settles a matching in-flight
        scale-in immediately instead of waiting a tick."""
        if self.node is None or not self.node.is_leader:
            return
        for row in self.ledger.pending("scale_in"):
            if row["target"] == uname:
                self.ledger.settle(
                    row["id"], "applied", reason="leave-observed"
                )

    # -- wire surface --------------------------------------------------

    def _relay_event(
        self, event: Dict[str, Any], row: Dict[str, Any]
    ) -> None:
        """Every ledger transition rides one small datagram to the hot
        standby (the ALERT relay discipline applied to decisions), so
        a promoted leader inherits cooldowns + in-flight decisions."""
        if self.node is None or not self.node.is_leader:
            return
        sb = self.node.standby_node()
        if sb is None or sb.unique_name == self.node.me.unique_name:
            return
        try:
            self.node.send(
                sb, MsgType.AUTOSCALE,
                {"row": row, "event": event["event"],
                 "cooldowns": dict(self.ledger.cooldowns)},
            )
            _M_AS_RELAYS.inc()
        except ValueError:
            log.warning("autoscale relay row over the datagram cap")

    async def _h_autoscale(self, msg: Message, addr) -> None:
        """Standby side of the decision relay: adopt the row + the
        cooldown ledger. Only the CURRENT leader's ledger is
        authoritative — a stale ex-leader's late datagram must not
        reopen settled decisions."""
        if msg.sender != self.node.leader_unique:
            return
        row = msg.data.get("row")
        cds = msg.data.get("cooldowns")
        if self.ledger.adopt(
            [row] if isinstance(row, dict) else [],
            cooldowns=cds if isinstance(cds, dict) else None,
        ):
            log.debug(
                "%s: adopted relayed decision %s (%s)",
                self.node.me.unique_name,
                row.get("id") if isinstance(row, dict) else None,
                msg.data.get("event"),
            )

    def summary(self) -> Dict[str, Any]:
        """Operator rollup: latest rows, cooldowns, pool floor
        evidence."""
        return {
            "rows": self.ledger.rows()[-16:],
            "cooldowns": dict(self.ledger.cooldowns),
            "min_pool_seen": self.min_pool_seen,
            "policy": {
                "floor": self.policy.floor,
                "ceiling": self.policy.ceiling,
            },
        }


# ----------------------------------------------------------------------
# deterministic replay + scoring
# ----------------------------------------------------------------------

def replay_decision_stream(
    ticks: Sequence[Dict[str, Any]],
    policy: Optional[AutoscalePolicy] = None,
) -> List[Dict[str, Any]]:
    """Drive a recorded snapshot schedule through a FRESH controller
    core (no node, no actuators). Pure function of its inputs: the
    same ticks and policy produce a byte-identical event stream
    (compare ``json.dumps(..., sort_keys=True)`` of the return) — how
    the bench proves the decision plane is seed-deterministic without
    pretending live cluster walls are reproducible."""
    ctl = AutoscaleController(policy=policy, clock=lambda: 0.0)
    for snap in ticks:
        ctl.step(snap)
    return ctl.ledger.stream()


def slo_violation_minutes(
    trace: Any,
    outcomes: Sequence[Any],
    bucket_s: float = 5.0,
    budget: float = 0.05,
) -> float:
    """Score an open-loop run as SLO-violation-MINUTES: the trace is
    cut into ``bucket_s`` buckets by arrival time (outcomes align with
    ``trace.arrivals`` by index — ``run_open_loop``'s contract) and a
    bucket is violating when more than ``budget`` of its arrivals
    missed their deadline or were shed/lost. The diurnal bench compares
    this integral between static and autoscaled provisioning."""
    if not trace.arrivals or not outcomes:
        return 0.0
    buckets: Dict[int, List[bool]] = {}
    for a, o in zip(trace.arrivals, outcomes):
        bad = not (
            getattr(o, "terminal", None) == "completed"
            and getattr(o, "deadline_met", False)
        )
        buckets.setdefault(int(a.t // bucket_s), []).append(bad)
    violating = sum(
        1 for rows in buckets.values()
        if (sum(rows) / len(rows)) > budget
    )
    return round(violating * bucket_s / 60.0, 4)
