"""Tracing, profiling, and structured logging.

The reference's observability is print-based wall-clock spans and a
debug.log (SURVEY §5: no tracing, no profiling). The TPU-native
equivalents:

- `profile()` — jax.profiler trace context producing TensorBoard /
  Perfetto traces of the XLA programs (compile + execute + transfers)
- `span()` — lightweight wall-clock spans collected into a process
  registry (the reference's `PUT runtime:` prints, structured)
- `jsonl_logging()` — one-JSON-object-per-line log formatting for
  machine-readable node logs
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from collections import defaultdict
from typing import Dict, Iterator, List, Optional


@contextlib.contextmanager
def profile(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (view with TensorBoard's profile
    plugin or Perfetto). Wrap a few representative steps, not a whole
    run — traces are large."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Spans:
    """Process-wide wall-clock span registry (mean/count per label)."""

    def __init__(self):
        self._acc: Dict[str, List[float]] = defaultdict(list)

    @contextlib.contextmanager
    def span(self, label: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self._acc[label].append(time.monotonic() - t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for label, xs in sorted(self._acc.items()):
            out[label] = {
                "count": float(len(xs)),
                "total_s": sum(xs),
                "mean_s": sum(xs) / len(xs),
                "max_s": max(xs),
            }
        return out

    def reset(self) -> None:
        self._acc.clear()


SPANS = Spans()
span = SPANS.span


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, ensure_ascii=False)


def jsonl_logging(
    path: Optional[str] = None, level: int = logging.INFO
) -> logging.Handler:
    """Install a JSON-lines handler on the root logger (file or stderr)."""
    handler: logging.Handler = (
        logging.FileHandler(path) if path else logging.StreamHandler()
    )
    handler.setFormatter(_JsonFormatter())
    root = logging.getLogger()
    root.addHandler(handler)
    if root.level > level:
        root.setLevel(level)
    return handler
