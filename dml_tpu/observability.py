"""Typed cluster metrics, tracing, profiling, and structured logging.

The reference system's defining operator surface is the coordinator's
live console: C1 prints the 10-second query rate and total query count
per model, C2 the per-query latency mean/percentiles/std per model, C3
confirms batch-size changes, C5 the current worker->batch assignments
(reference worker.py:1394-1428, 1744-1808). This module is the
TPU-native generalization of that console: a typed, process-wide
metrics registry every subsystem writes into, plus the exposition
surfaces (Prometheus text, JSON dumps, leader-aggregated METRICS_PULL,
bench-artifact blocks) that make the numbers reachable.

Metric model
------------

- ``Counter`` — monotonically increasing totals (queries served,
  tokens decoded, datagrams sent). Merge across nodes by summing.
- ``Gauge`` — instantaneous values (active slots, queue depth,
  trailing query rate). Merge by summing (cluster capacity view).
- ``Histogram`` — streaming distributions over FIXED LOG-SPACED
  buckets; p50/p95/p99 are computed from the bucket counts with
  geometric interpolation, so percentiles need O(buckets) memory, are
  mergeable across nodes, and never require keeping raw samples.

All three take labels (``model=``, ``role=``, ``peer=``, ``type=``);
one metric object fans out into per-label-set children. Updates are
host-side, O(1), lock-protected dict writes — they live OUTSIDE any
jitted device step, so instrumentation cannot perturb a compiled
program (the continuous-batching decode path updates a handful of
counters per CHUNK dispatch, not per token).

Reference C1–C5 -> registry map
-------------------------------

- **C1** (per-model query count + 10 s rate): ``jobs_queries_total``
  counter + ``jobs_query_rate_per_s`` gauge (scheduler refreshes the
  trailing-window rate on every batch ACK).
- **C2** (per-query processing-time mean/std/percentiles):
  ``jobs_query_latency_seconds`` histogram per model — mean from
  sum/count, p50/p95/p99 from the log buckets. The exact-sample C2
  console (``Scheduler.c2_stats``) remains for parity with the
  reference; the histogram is the mergeable cluster-wide form.
- **C3** (batch size): ``jobs_batch_exec_seconds`` per model shows the
  effect; the authoritative setting stays in the scheduler cost model.
- **C5** (worker->batch assignments): ``jobs_workers_busy`` gauge +
  ``Scheduler.c5_assignments()`` for the exact map.

Beyond the reference (net-new subsystems get the same treatment):
``lm_server_*`` (queue wait, prefill dispatch, per-step decode tokens,
slot occupancy, compile events, readback stalls), ``worker_*``
(fetch/infer/put stage timings, decode-cache hits),
``jobs_pipeline_depth`` / ``jobs_depth_*`` (the probe-adaptive
worker-pipelining controller: depth in force, per-phase probe-rate
histogram by depth, probe-cycle counters by trigger and aborts),
``jobs_group_*`` (tensor-parallel worker groups, jobs/groups.py:
``jobs_group_formed`` gauge — 1 while every member is alive and
schedulable, ``jobs_group_members_alive`` gauge,
``jobs_group_degradations_total`` / ``jobs_group_reforms_total``
edge counters, ``jobs_group_batches_total`` batches served on a
group's sharded engine, ``jobs_group_requeues_total`` primary
in-flight batches requeued by a degradation — all labeled
``group=``),
``lm_sharded_*`` (sharded LM serving, inference/lm_sharded.py:
``lm_sharded_batches_total`` LM batches served on a group engine
labeled ``group=``/``mode=`` (resident|gather|disagg),
``lm_sharded_tokens_total`` generated tokens delivered by
group-sharded serving, ``lm_sharded_prefill_slabs_total`` KV-cache
slabs built by prefill-role workers),
``jobs_kv_handoff_*`` (the disaggregated prefill->decode handoff:
``jobs_kv_handoff_total`` labeled ``result=`` ok|fallback — a
fallback means the decode primary prefilled locally after a failed
handoff, a throughput event never a correctness one —
``jobs_kv_handoff_bytes_total`` serialized slab bytes pulled over
the data plane, ``jobs_kv_handoff_seconds`` per-batch prefill RPC +
slab pull wall),
``request_*`` (the SLO-aware request front door, dml_tpu/ingress/:
``request_admitted_total`` / ``request_completed_total`` per SLO
class, ``request_shed_total`` admission sheds labeled
``slo=``/``reason=`` (queue_full | deadline_unmeetable),
``request_rejected_total`` post-admission typed rejections,
``request_deadline_miss_total`` completions past their deadline —
labeled ``stage=`` with the miss's DOMINANT stage from its trace
attribution (formation | dispatch | fetch | infer | put |
unattributed), so the counter alone says WHERE the tail is lost,
``request_queue_wait_seconds`` admission->dispatch wait and
``request_e2e_latency_seconds`` admission->completion latency
histograms per class — the p50/p95/p99 source of the
``request_serving`` bench section — ``request_in_flight`` gauge,
``request_batch_fill_fraction`` / ``request_batch_formation_seconds``
continuous-batch-formation quality, and
``request_stream_tokens_total`` LM tokens pushed into per-request
data-plane token streams on workers),
``cluster_*`` (SWIM suspicion/failure/false-positive events,
alive-node gauge), ``membership_gossip_*`` (the bounded delta-gossip
piggyback: payloads built and member entries carried, labeled
``mode=`` delta|full — the O(K)-vs-O(N) per-datagram story the
``control_plane_scale`` bench scores), ``metrics_relay_*`` (two-level
METRICS_PULL aggregation: relay-shard pulls by ``role=`` leader|relay,
per-shard wall, and shards that fell back to direct pulls),
``store_report_delta_*`` (the replica inventory re-report fan-in:
reports and entries by ``kind=`` delta|full plus unchanged ticks that
sent nothing), ``transport_*`` (datagram + byte counters by
message type), and ``store_*`` (put/get/replication timing and
counts).

Exposition
----------

- ``METRICS.snapshot()`` — JSON-able dump (sparse buckets) used by the
  ``METRICS_PULL`` wire message: the leader pulls every node's
  snapshot and ``merge_snapshots`` folds them into one cluster view
  (``Node.pull_cluster_metrics``), the TPU-native analog of the
  reference coordinator's console.
- ``to_prometheus_text()`` — Prometheus exposition format (CLI
  ``profile metrics prom``), scrape-ready.
- ``bench_metrics_block()`` — the ``metrics`` block embedded in bench
  artifacts so BENCH_r*.json carries per-stage breakdowns
  (tools/claim_check.py validates its presence from round 6 on).

In-process simulations (tests) run many nodes in ONE process sharing
this module-global registry; snapshots carry the pid and
``merge_snapshots`` counts each process once, so the sim's cluster
totals equal the (shared) registry instead of multiplying by the node
count, while real one-process-per-node deployments sum normally.

Also here, unchanged from the seed: ``profile()`` (jax.profiler trace
context), ``span()`` (wall-clock spans), ``jsonl_logging()``.

Metric map (lint-enforced)
--------------------------

The complete registry, one metric per 4-space-indented line. This map
is MACHINE-READ: ``tools/dmllint.py`` (rule drift-metrics-map, run by
tier-1 via tests/test_dmllint.py) fails when a metric is registered in
``dml_tpu/`` but missing here, or listed here but registered nowhere —
the map cannot silently desynchronize from the code again. Add the
line when you add the metric.

    alert_fired_total                alert firing transitions by name= severity=
    alert_firing                     currently-firing alerts by name=
    alert_relays_total               ledger transitions relayed to standby
    alert_resolved_total             alert resolved transitions by name=
    autoscale_decisions_total        decision-ledger transitions by kind= event=
    autoscale_pool_size              worker-pool size the autoscaler last observed
    autoscale_relays_total           decision events relayed to standby
    autoscale_suppressed_total       decisions withheld by reason= (liar/floor/...)
    cluster_alive_nodes              SWIM live-member gauge
    cluster_failover_recovery_seconds  chaos: leader-kill -> converged wall
    cluster_false_positives_total    SWIM suspicions that proved alive
    cluster_node_failures_total      SWIM members declared failed
    cluster_suspicions_total         SWIM suspicion events
    coordinator_batch_acks_total     batch ACKs seen by the coordinator
    jobs_batch_exec_seconds          per-model batch execution wall
    jobs_completed_total             jobs reaching terminal success
    jobs_depth_probe_aborts_total    depth probes aborted (stall/timeout)
    jobs_depth_probe_qps             probe-phase throughput by depth
    jobs_depth_probes_total          depth probe cycles by trigger
    jobs_failed_total                jobs retired at the failure cap
    jobs_group_batches_total         batches served on a group engine
    jobs_group_degradations_total    group formed -> degraded edges
    jobs_group_formed                1 while a group is schedulable
    jobs_group_members_alive         live members per group
    jobs_group_reforms_total         group degraded -> formed edges
    jobs_group_requeues_total        primary in-flight batches requeued
    jobs_group_reshape_chips         chips in the mesh in force per group
    jobs_group_reshapes_total        collapsed-shape changes (reform ladder)
    jobs_kv_handoff_bytes_total      serialized KV slab bytes pulled
    jobs_kv_handoff_seconds          prefill RPC + slab pull wall
    jobs_kv_handoff_total            disagg handoffs by result ok|fallback
    jobs_pipeline_depth              worker-pipelining depth in force
    jobs_preemptions_total           running batches preempted
    jobs_queries_total               C1 per-model query counter
    jobs_query_latency_seconds       C2 per-query latency histogram
    jobs_query_rate_per_s            C1 trailing 10 s query rate
    jobs_queue_depth                 schedulable batches per model
    jobs_requeues_total              batches requeued after worker loss
    jobs_workers_busy                C5 workers-with-assignments gauge
    lm_kv_cache_bytes                prefix-cache resident host bytes
    lm_kv_cache_entries              live prefix-cache entries
    lm_kv_cache_evictions_total      prefix-cache entries evicted
    lm_kv_cache_hits_total           warm starts from cached prefixes
    lm_kv_cache_misses_total         lookups with no usable prefix
    lm_kv_cache_tokens_saved_total   prompt tokens not re-prefilled
    lm_server_compile_events_total   decode-graph compile events
    lm_server_decode_tokens_total    tokens decoded (all slots)
    lm_server_prefill_dispatch_seconds  prefill dispatch wall
    lm_server_queue_wait_seconds     request queue wait
    lm_server_readback_seconds       device->host readback stalls
    lm_server_requests_completed_total  LM requests finished
    lm_server_requests_total         LM requests admitted
    lm_server_slot_occupancy         busy decode slots per dispatched step
    lm_server_slots_active           busy decode slots
    lm_server_slots_total            configured decode slots
    lm_server_step_seconds           decode step wall
    lm_server_steps_total            decode steps executed
    lm_sharded_batches_total         LM batches on a group engine by mode
    lm_sharded_prefill_slabs_total   KV slabs built by prefill workers
    lm_sharded_tokens_total          tokens from group-sharded serving
    lm_specdec_accepted_total        draft tokens accepted by verify
    lm_specdec_disabled_total        spec-decode disable events by reason
    lm_specdec_proposed_total        draft tokens proposed to verify
    membership_gossip_entries_total  gossip entries carried by mode
    membership_gossip_exchanges_total  gossip payloads built by mode
    membership_join_admitted_total   runtime joins admitted (new|rejoin)
    membership_join_rejected_total   JOIN_REQUESTs rejected by reason
    membership_leave_rejected_total  LEAVE announcements rejected by reason
    membership_leaves_total          graceful departures retired
    membership_universe_epoch        dynamic node-table version in force
    metrics_relay_fallback_total     relay shards fallen back to direct
    metrics_relay_pulls_total        relay-shard aggregations by role
    metrics_relay_seconds            relay shard pull + pre-merge wall
    request_admitted_total           front-door admissions per SLO class
    request_batch_fill_fraction      formed-batch fill quality
    request_batch_formation_seconds  batch formation wall
    request_completed_total          request terminals per SLO class
    request_deadline_miss_total      completions past their deadline
    request_e2e_latency_seconds      admission -> completion latency
    request_in_flight                admitted, not yet terminal
    request_queue_wait_seconds       admission -> dispatch wait
    request_rejected_total           post-admission typed rejections
    request_session_affinity_evictions_total  session rows aged out
    request_session_affinity_hits_total  sessions routed to KV holder
    request_session_affinity_misses_total  sessions with no live target
    request_shed_total               admission sheds by slo= reason=
    request_stream_tokens_total      tokens pushed into request streams
    signal_crosscheck_flags_total    workers convicted by ACK-wall check
    signal_monitor_transitions_total burn-monitor transitions by signal= to=
    signal_samples_total             signal-plane window sample ticks
    signal_window_value              latest windowed sample per key=
    store_corruption_detected_total  sha256 mismatches quarantined
    store_deletes_total              delete operations
    store_get_seconds                GET wall
    store_gets_total                 GET operations
    store_put_seconds                PUT wall
    store_puts_total                 PUT operations
    store_repair_seconds             chaos: corruption -> repaired wall
    store_replication_failures_total replication attempts failed
    store_replication_seconds        replication wall
    store_replications_total         replication operations
    store_report_delta_entries_total re-report entries carried by kind
    store_report_delta_skipped_total re-report ticks with nothing to say
    store_report_delta_total         inventory re-reports by kind
    store_write_failures_total       local write failures (ENOSPC etc.)
    tracing_exemplars_total          tail-exemplar span captures by kind
    tracing_spans_dropped_total      flight-recorder ring evictions
    tracing_spans_total              finished spans observed by sampled=
    train_effective_batch            shard_batch x world by run=
    train_resharding_total           ckpt-restore re-shards by reason=
    train_step_wall_seconds          dispatch->applied step wall
    train_steps_total                global steps applied exactly once
    transport_bytes_received_total   datagram bytes in by msg type
    transport_bytes_sent_total       datagram bytes out by msg type
    transport_malformed_dropped_total  frames dying in Message.unpack
    transport_packets_delayed_total  link-shaper delayed emits
    transport_packets_dropped_inbound_total  inbound filter drops
    transport_packets_dropped_total  loss-injection outbound drops
    transport_packets_duplicated_total  link-shaper duplicate emits
    transport_packets_received_total datagrams in by msg type
    transport_packets_sent_total     datagrams out by msg type
    worker_batch_failures_total      worker batch executions failed
    worker_batches_total             worker batch executions
    worker_decode_cache_hits_total   decoded-input cache hits
    worker_decode_cache_misses_total decoded-input cache misses
    worker_fetch_seconds             worker input-fetch stage wall
    worker_infer_seconds             worker inference stage wall
    worker_put_seconds               worker output-put stage wall
"""

from __future__ import annotations

import bisect
import contextlib
import json
import logging
import math
import os
import threading
import time
import weakref
from collections import defaultdict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# ----------------------------------------------------------------------
# typed metrics registry
# ----------------------------------------------------------------------

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_key(name: str, key: _LabelKey) -> str:
    if not key:
        return name
    inner = ",".join(f"{k}={v}" for k, v in key)
    return f"{name}{{{inner}}}"


def log_buckets(
    lo: float = 1e-4, hi: float = 100.0, per_decade: int = 6
) -> Tuple[float, ...]:
    """Fixed log-spaced bucket edges: ``per_decade`` edges per decade
    from ``lo`` up to (at least) ``hi``. Constant ratio between
    adjacent edges bounds the worst-case percentile error to one
    ratio step regardless of the value's magnitude."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(f"bad bucket spec lo={lo} hi={hi}/{per_decade}")
    edges: List[float] = []
    i = 0
    while True:
        e = lo * 10.0 ** (i / per_decade)
        edges.append(e)
        if e >= hi:
            return tuple(edges)
        i += 1


#: default edges for latency-in-seconds histograms: 100 µs .. 100 s
DEFAULT_TIME_BUCKETS = log_buckets(1e-4, 100.0, per_decade=6)


class _Child:
    """A metric bound to one label set. Holds only (parent, key): the
    value slots live in the parent, so a registry reset never strands
    a cached handle."""

    __slots__ = ("_m", "_key")

    def __init__(self, metric: "_Metric", key: _LabelKey):
        self._m = metric
        self._key = key


class _CounterChild(_Child):
    def inc(self, n: float = 1.0) -> None:
        m = self._m
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0.0) + n


class _GaugeChild(_Child):
    def set(self, v: float) -> None:
        m = self._m
        with m._lock:
            m._values[self._key] = float(v)

    def inc(self, n: float = 1.0) -> None:
        m = self._m
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0.0) + n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class _HistChild(_Child):
    def observe(self, v: float) -> None:
        m = self._m
        v = float(v)
        with m._lock:
            st = m._values.get(self._key)
            if st is None:
                # [count, sum, min, max, bucket_counts]; the last
                # bucket is the +Inf overflow
                st = m._values[self._key] = [
                    0, 0.0, math.inf, -math.inf, [0] * (len(m.edges) + 1)
                ]
            st[0] += 1
            st[1] += v
            if v < st[2]:
                st[2] = v
            if v > st[3]:
                st[3] = v
            st[4][bisect.bisect_left(m.edges, v)] += 1


class _Metric:
    kind = ""
    _child_cls = _Child

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[_LabelKey, _Child] = {}
        self._values: Dict[_LabelKey, Any] = {}

    def labels(self, **labels: Any):
        """Bind a label set; returns a cached child handle. Hot paths
        should call this once and keep the handle."""
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, self._child_cls(self, key)
                )
        return child

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def items(self) -> List[Tuple[_LabelKey, Any]]:
        with self._lock:
            return list(self._values.items())


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        self.labels(**labels).inc(n)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._values.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float, **labels: Any) -> None:
        self.labels(**labels).set(v)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._values.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    kind = "histogram"
    _child_cls = _HistChild

    def __init__(
        self,
        name: str,
        help: str = "",
        edges: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ):
        super().__init__(name, help)
        edges = tuple(float(e) for e in edges)
        if list(edges) != sorted(edges) or len(set(edges)) != len(edges):
            raise ValueError(f"{name}: bucket edges must strictly increase")
        self.edges = edges

    def observe(self, v: float, **labels: Any) -> None:
        self.labels(**labels).observe(v)


class MetricsRegistry:
    """Process-wide named-metric table. `counter`/`gauge`/`histogram`
    are get-or-create (idempotent by name; a kind clash raises), so
    any module can declare its metrics at import time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # weakly-held callables run before every exposition to refresh
        # DERIVED values (e.g. the scheduler's trailing query rate,
        # which must decay at read time, not freeze at its last
        # event-driven update)
        self._collectors: List[weakref.ref] = []

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)  # type: ignore[return-value]

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help: str = "",
        edges: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get(Histogram, name, help, edges=edges)  # type: ignore[return-value]

    def reset(self) -> None:
        """Zero every metric's values. Registered metric objects (and
        any cached child handles) stay valid — tests isolate state
        without invalidating module-level handles."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    # -- exposition ----------------------------------------------------

    def add_collector(self, fn: Any) -> None:
        """Register a BOUND METHOD to run before every exposition
        (snapshot / Prometheus text), for gauges derived from state
        that only the owner can read — e.g. a trailing-window rate
        that must decay on an idle system. Held weakly: the collector
        dies with its owner, so short-lived instances (tests, sims)
        never accumulate."""
        ref = (
            weakref.WeakMethod(fn)
            if hasattr(fn, "__self__")
            else weakref.ref(fn)
        )
        with self._lock:
            self._collectors.append(ref)

    def _run_collectors(self) -> None:
        with self._lock:
            refs = list(self._collectors)
        dead = False
        for r in refs:
            fn = r()
            if fn is None:
                dead = True
                continue
            try:
                fn()
            except Exception:  # a collector must never break exposition
                logging.getLogger(__name__).debug(
                    "metrics collector failed", exc_info=True
                )
        if dead:
            with self._lock:
                self._collectors = [
                    r for r in self._collectors if r() is not None
                ]

    def snapshot(self, node: Optional[str] = None) -> Dict[str, Any]:
        """JSON-able dump. Histogram buckets are sparse ({index:
        count} for nonzero buckets) to keep METRICS_PULL replies well
        under the UDP frame cap."""
        out: Dict[str, Any] = {
            "v": 1,
            "proc": os.getpid(),
            "ts": time.time(),
            "counters": {},
            "gauges": {},
            "histograms": {},
        }
        if node is not None:
            out["node"] = node
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for key, val in m.items():
                fk = _fmt_key(m.name, key)
                if m.kind == "counter":
                    out["counters"][fk] = val
                elif m.kind == "gauge":
                    out["gauges"][fk] = val
                else:
                    count, total, mn, mx, buckets = val
                    edges = m.edges  # type: ignore[attr-defined]
                    out["histograms"][fk] = {
                        "count": count,
                        "sum": total,
                        "min": mn if count else None,
                        "max": mx if count else None,
                        # the common edge set compresses to a sentinel
                        # (~37 floats per labeled entry otherwise —
                        # real pressure against the UDP frame cap)
                        "edges": (
                            "default"
                            if edges == DEFAULT_TIME_BUCKETS
                            else list(edges)
                        ),
                        "bkt": {
                            str(i): c
                            for i, c in enumerate(buckets)
                            if c
                        },
                    }
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain version 0.0.4)."""
        lines: List[str] = []
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            items = m.items()
            if not items:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in sorted(items):
                if m.kind in ("counter", "gauge"):
                    lines.append(f"{m.name}{_prom_labels(key)} {_g(val)}")
                    continue
                count, total, _mn, _mx, buckets = val
                cum = 0
                for i, edge in enumerate(m.edges):  # type: ignore[attr-defined]
                    cum += buckets[i]
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_prom_labels(key, le=_g(edge))} {cum}"
                    )
                lines.append(
                    f"{m.name}_bucket{_prom_labels(key, le='+Inf')} {count}"
                )
                lines.append(f"{m.name}_sum{_prom_labels(key)} {_g(total)}")
                lines.append(f"{m.name}_count{_prom_labels(key)} {count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _g(v: float) -> str:
    return f"{float(v):g}"


def _prom_labels(key: _LabelKey, **extra: str) -> str:
    pairs = list(key) + sorted(extra.items())
    if not pairs:
        return ""
    inner = ",".join(
        '{}="{}"'.format(
            k, str(v).replace("\\", r"\\").replace('"', r"\"")
        )
        for k, v in pairs
    )
    return f"{{{inner}}}"


#: the process-wide registry every subsystem writes into
METRICS = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return METRICS.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return METRICS.gauge(name, help)


def histogram(
    name: str, help: str = "", edges: Sequence[float] = DEFAULT_TIME_BUCKETS
) -> Histogram:
    return METRICS.histogram(name, help, edges)


# ----------------------------------------------------------------------
# snapshot math: percentiles, summaries, cross-node merge
# ----------------------------------------------------------------------


def _entry_edges(entry: Dict[str, Any]) -> Optional[Sequence[float]]:
    """Resolve a snapshot entry's bucket edges: the ``"default"``
    sentinel (wire compression), an explicit list, or None for a
    bucket-stripped entry."""
    e = entry.get("edges")
    if e == "default":
        return DEFAULT_TIME_BUCKETS
    if isinstance(e, (list, tuple)) and e:
        return e
    return None


def hist_quantile(entry: Dict[str, Any], q: float) -> Optional[float]:
    """Quantile estimate from a snapshot histogram entry: walk the
    cumulative bucket counts to the target rank, then geometrically
    interpolate inside the landing bucket (log-spaced edges make the
    geometric mean the max-likelihood point). Clamped to the observed
    [min, max]; the overflow bucket reports the observed max.

    The rank base is the number of samples the BUCKETS represent
    (``bkt_count`` on merged entries), not the total count: a cluster
    merge may fold in bucket-stripped nodes whose samples contribute
    to count/sum/mean but are invisible to the buckets, and ranking
    over the inflated total would systematically skew the walk toward
    the high buckets. Percentiles then describe the bucketed
    subpopulation; an entry with no bucketed samples returns None."""
    count = entry.get("count", 0)
    if not count:
        return None
    edges = _entry_edges(entry)
    if edges is None:  # bucket-stripped entry: percentiles unknowable
        return None
    buckets = entry.get("bkt", {})
    mn = entry.get("min")
    mx = entry.get("max")
    base = entry.get("bkt_count", count)
    if not base:
        return None
    target = q * base
    cum = 0.0
    for i in range(len(edges) + 1):
        c = buckets.get(str(i), 0)
        if not c:
            continue
        if cum + c >= target:
            if i >= len(edges):  # overflow: only the max is known
                return mx
            hi = edges[i]
            lo = edges[i - 1] if i > 0 else (
                mn if mn and mn > 0 else hi / 10.0
            )
            if lo <= 0:
                lo = hi / 10.0
            frac = max(0.0, min(1.0, (target - cum) / c))
            est = lo * (hi / lo) ** frac
            if mn is not None:
                est = max(est, mn)
            if mx is not None:
                est = min(est, mx)
            return est
        cum += c
    return mx


def summarize_histogram(entry: Dict[str, Any]) -> Dict[str, Any]:
    """C2-style roll-up of a snapshot histogram entry: count, mean,
    min/max, p50/p95/p99."""
    count = entry.get("count", 0)
    out: Dict[str, Any] = {"count": count}
    if not count:
        return out
    out["mean"] = entry.get("sum", 0.0) / count
    out["min"] = entry.get("min")
    out["max"] = entry.get("max")
    for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
        out[name] = hist_quantile(entry, q)
    bc = entry.get("bkt_count")
    if bc is not None and bc < count:
        # some merged-in nodes were bucket-stripped: the percentiles
        # above describe only these samples (mean/min/max are global)
        out["percentile_count"] = bc
    return out


def summarize_snapshot(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Human/CLI view of a snapshot (or merged cluster snapshot):
    counters and gauges verbatim, histograms rolled up to
    count/mean/percentiles."""
    return {
        "counters": dict(snap.get("counters", {})),
        "gauges": dict(snap.get("gauges", {})),
        "histograms": {
            k: summarize_histogram(h)
            for k, h in sorted(snap.get("histograms", {}).items())
        },
    }


def merge_snapshots(
    snaps: Sequence[Dict[str, Any]], dedupe_by_proc: bool = True
) -> Dict[str, Any]:
    """Fold per-node snapshots into one cluster view: counters and
    gauges sum, histograms merge bucket-wise (same-name histograms
    must share edges — they do, the metric declarations are code).

    ``dedupe_by_proc`` counts each producing PROCESS once: in-process
    simulations run every node over one shared registry, and summing
    N identical copies would report an N× phantom cluster. Real
    deployments are one process per node, so nothing is dropped.

    Inputs may themselves be MERGED blobs (the two-level relay
    aggregation pre-merges each shard): such a blob carries ``procs``
    (every process it folded) instead of ``proc``, and is skipped
    only when EVERY one of its processes was already counted — so an
    in-process sim's relay blobs dedupe against the leader's own
    snapshot exactly like direct pulls do, while real multi-process
    shards all count. The output carries ``procs`` and a
    ``merged_from`` that sums nested counts, keeping the node count
    honest through both aggregation levels."""
    out: Dict[str, Any] = {
        "v": 1,
        "counters": {},
        "gauges": {},
        "histograms": {},
        "merged_from": 0,
    }
    seen_procs = set()
    for snap in snaps:
        procs = snap.get("procs")
        if not isinstance(procs, list):
            proc = snap.get("proc")
            procs = [proc] if proc is not None else []
        if dedupe_by_proc and procs and all(p in seen_procs for p in procs):
            continue
        seen_procs.update(procs)
        out["merged_from"] += int(snap.get("merged_from", 1) or 1)
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in snap.get("gauges", {}).items():
            out["gauges"][k] = out["gauges"].get(k, 0.0) + v
        for k, h in snap.get("histograms", {}).items():
            cur = out["histograms"].get(k)
            if cur is None:
                cur = out["histograms"][k] = {
                    "count": 0,
                    "sum": 0.0,
                    "min": None,
                    "max": None,
                    "bkt": {},
                    # how many of `count` the buckets represent: a
                    # bucket-stripped node's samples join count/sum
                    # (mean stays exact) but not the buckets, and
                    # quantile ranking must know the difference
                    "bkt_count": 0,
                }
            cur["count"] += h.get("count", 0)
            cur["sum"] += h.get("sum", 0.0)
            for bound, pick in (("min", min), ("max", max)):
                v = h.get(bound)
                if v is not None:
                    cur[bound] = v if cur[bound] is None else pick(cur[bound], v)
            if _entry_edges(h) is None:  # stripped: no buckets to fold
                continue
            if "edges" not in cur:  # first bucketed contributor
                cur["edges"] = h["edges"]
            cur["bkt_count"] += h.get("bkt_count", h.get("count", 0))
            for i, c in h.get("bkt", {}).items():
                cur["bkt"][i] = cur["bkt"].get(i, 0) + c
    out["procs"] = sorted(seen_procs)
    return out


def strip_buckets(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Shrink a snapshot for a constrained wire frame: histogram
    entries keep count/sum/min/max (mean stays computable) but drop
    the bucket counts (and with them percentiles). The METRICS_PULL
    handler falls back to this when the full snapshot would exceed
    the UDP frame cap."""
    out = dict(snap)
    out["histograms"] = {
        k: {
            kk: vv
            for kk, vv in h.items()
            if kk not in ("bkt", "edges", "bkt_count")
        }
        for k, h in snap.get("histograms", {}).items()
    }
    out["stripped"] = True
    return out


def bench_metrics_block() -> Dict[str, Any]:
    """The ``metrics`` block bench.py embeds in every artifact:
    summarized registry contents, so BENCH_r*.json carries per-stage
    breakdowns (lm_server decode counters, worker stage timings,
    transport totals) alongside the headline numbers.
    tools/claim_check.py validates this block's presence and shape."""
    block = summarize_snapshot(METRICS.snapshot())
    block["schema"] = 1
    return block


# ----------------------------------------------------------------------
# jax profiling + wall-clock spans + JSONL logging (seed surface)
# ----------------------------------------------------------------------


@contextlib.contextmanager
def profile(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (view with TensorBoard's profile
    plugin or Perfetto). Wrap a few representative steps, not a whole
    run — traces are large."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Spans:
    """Process-wide wall-clock span registry (mean/count per label)."""

    def __init__(self):
        self._acc: Dict[str, List[float]] = defaultdict(list)

    @contextlib.contextmanager
    def span(self, label: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self._acc[label].append(time.monotonic() - t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for label, xs in sorted(self._acc.items()):
            out[label] = {
                "count": float(len(xs)),
                "total_s": sum(xs),
                "mean_s": sum(xs) / len(xs),
                "max_s": max(xs),
            }
        return out

    def reset(self) -> None:
        self._acc.clear()


SPANS = Spans()
span = SPANS.span


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, ensure_ascii=False)


def jsonl_logging(
    path: Optional[str] = None, level: int = logging.INFO
) -> logging.Handler:
    """Install a JSON-lines handler on the root logger (file or stderr)."""
    handler: logging.Handler = (
        logging.FileHandler(path) if path else logging.StreamHandler()
    )
    handler.setFormatter(_JsonFormatter())
    root = logging.getLogger()
    root.addHandler(handler)
    if root.level > level:
        root.setLevel(level)
    return handler
