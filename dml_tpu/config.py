"""Cluster specification, node identity, and timing configuration.

Replaces the reference's hand-edited static tables (config.py:4-89,
nodes.py:1-35) with a declarative, serializable spec: no hardcoded
hostnames, no credential files (the reference reads SSH passwords from
password.txt, config.py:29-37 — our data plane is credential-free TCP),
and the ring topology is computed from the node list instead of written
out by hand (reference GLOBAL_RING_TOPOLOGY, config.py:67-89).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True, order=True)
class NodeId:
    """Immutable node identity (reference: nodes.py Node).

    Ordering is (host, port) lexicographic; election rank uses
    `rank` when provided so operators can pin coordinator preference
    (the reference hardcoded H1 leader / H2 standby; we elect by
    highest rank with (host, port) as tiebreak).
    """

    host: str
    port: int
    name: str = ""
    rank: int = 0

    @property
    def unique_name(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return self.name or self.unique_name


@dataclass(frozen=True)
class Timing:
    """Failure-detector timing constants (reference config.py:4-10).

    Reference deployed values: ping every 12 s, ACK timeout 10 s,
    suspect cleanup 30 s, M=3 ring successors. Defaults here are the
    README's tuned example (README.md:68-78) scaled for tests; real
    deployments load their own.
    """

    ping_interval: float = 2.5
    ack_timeout: float = 2.0
    cleanup_time: float = 10.0
    missed_acks_to_suspect: int = 3
    leader_rpc_timeout: float = 20.0  # reference worker.py:1123-1135


@dataclass(frozen=True)
class StoreConfig:
    """Replicated-store knobs (reference leader.py:60, file_service.py:8-11)."""

    replication_factor: int = 4
    max_versions: int = 5
    root: str = "~/.dml_tpu/store"
    download_dir: str = "~/.dml_tpu/downloads"
    cleanup_on_startup: bool = False

    def store_path(self) -> str:
        return os.path.expanduser(self.root)

    def download_path(self) -> str:
        return os.path.expanduser(self.download_dir)


@dataclass(frozen=True)
class MeshSpec:
    """TPU device-mesh specification for the compute path.

    Axis sizes of -1 mean "fill with remaining devices". The inference
    engine shards batches over `dp`; model parallelism (when enabled)
    shards weights over `tp`; sequence parallelism (ring attention)
    uses `sp`.
    """

    dp: int = -1
    tp: int = 1
    sp: int = 1
    pp: int = 1  # pipeline stages (parallel/pipeline.py)
    ep: int = 1  # expert shards (parallel/moe.py)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {
            "dp": self.dp, "tp": self.tp, "sp": self.sp,
            "pp": self.pp, "ep": self.ep,
        }
        fixed = 1
        free = None
        for ax, s in sizes.items():
            if s == -1:
                if free is not None:
                    raise ValueError("only one mesh axis may be -1")
                free = ax
            else:
                fixed *= s
        if free is not None:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[free] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


@dataclass(frozen=True)
class WorkerGroupSpec:
    """A tensor-parallel serving group: nodes that pool their chips
    into ONE dp×tp worker (Kumar et al.'s pod-slice serving unit; the
    reference has no notion of this — every VM is its own whole-model
    replica, models.py:26,51).

    `members` are node names (e.g. "H4") or unique names; they must
    exist in the node table and belong to at most one group. `mesh` is
    the group's device layout over the pooled chips: `dp` shards
    batches, `tp` shards weight storage. The group serves as a single
    scheduler-visible worker (the deterministic first member by
    unique name is its primary) only while EVERY member is alive and
    schedulable; losing any member degrades it back to the surviving
    single-chip engines (jobs/groups.py).

    `lm_models` names LM serving models (register_lm names) this
    group's engine serves with weights RESIDENT tp-sharded in HBM
    (inference/lm_sharded.py). Like the member list it is static
    config: the coordinator reads it to decide whether an LM round
    may keep the group collapsed to one weighted slot — a group that
    does not declare the round's LM model falls back to single-chip
    slots for that round (the PR-5 behavior), because collapsing
    would model throughput the primary's engine cannot deliver.

    `roles` optionally splits the group into PREFILL and DECODE
    serving roles for disaggregated LM serving (member name ->
    "prefill" | "decode"). Prefill-role members run the chunked
    prompt prefill and hand the serialized KV-cache slab to the
    decode-role primary over the TCP store data plane; decode streams
    tokens through the normal job completion path. Empty = no
    disaggregation (every chip does both). Role assignment living
    HERE (not in a runtime protocol) means degradation/reform and
    failover derive the same view from spec + liveness, exactly like
    membership itself.

    A `mesh` with ``pp > 1`` serves the group's `lm_models` PIPELINE-
    parallel (inference/lm_sharded.py PipelinedLMBackend): each
    member holds only ``n_layers/pp`` of the layer stack, opening
    models DEEPER than one member's HBM. `hbm_bytes` (optional, 0 =
    unchecked) declares a member's HBM budget in bytes; the LM group
    wiring refuses to start a layout whose per-member weight bytes
    (`pp_hbm_report`) exceed it — a model bigger than the budget must
    be served through a pp axis, never silently OOM-ed at first
    batch."""

    name: str
    members: Tuple[str, ...] = ()
    mesh: MeshSpec = field(default_factory=lambda: MeshSpec(dp=-1, tp=1))
    lm_models: Tuple[str, ...] = ()
    roles: Dict[str, str] = field(default_factory=dict)
    hbm_bytes: int = 0


@dataclass
class ClusterSpec:
    """The whole-cluster config: node table + ring + timing + store.

    The reference's equivalent is the hand-maintained H1..H10 table and
    GLOBAL_RING_TOPOLOGY dict (config.py:54-89), duplicated into
    `introduce process/config.py`. Here there is one spec, serializable
    to JSON, shared by every role including the introducer.
    """

    nodes: List[NodeId] = field(default_factory=list)
    introducer: Optional[NodeId] = None
    #: tensor-parallel serving groups (jobs/groups.py); empty = every
    #: node serves alone (the reference's one-replica-per-VM shape)
    worker_groups: List[WorkerGroupSpec] = field(default_factory=list)
    ring_k: int = 3  # number of ping successors (reference M=3, config.py:4)
    timing: Timing = field(default_factory=Timing)
    store: StoreConfig = field(default_factory=StoreConfig)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    testing: bool = False
    packet_drop_pct: float = 0.0  # loss-injection seam (reference protocol.py:10)
    # ---- gossip piggyback protocol (cluster/membership.py) ----
    # "delta": every PING/ACK carries a BOUNDED member subset — the
    # sender's own entry, the `gossip_delta_k` entries with the
    # highest recent-change priority (fewest piggybacks since their
    # status last changed, newest timestamp first), and a seeded
    # random tail of `gossip_delta_tail` others — with the FULL table
    # exchanged only at join (INTRODUCE_ACK), at the dead-peer
    # anti-entropy probe, and every `gossip_full_every`-th piggyback.
    # "full": the reference full-table piggyback (O(N) entries per
    # datagram — the measured baseline the scale bench scores
    # against). At small N (≤ 1 + k + tail members) delta mode emits
    # the full table anyway, so the protocols are bit-identical there.
    gossip_protocol: str = "delta"
    gossip_delta_k: int = 8
    gossip_delta_tail: int = 4
    gossip_full_every: int = 20
    # >0: the coordinator snapshots scheduler state into the store
    # every N seconds while jobs are in flight (full-restart survival
    # without operator-driven checkpoint-jobs); 0 disables
    jobs_checkpoint_interval: float = 0.0

    # ---- lookups (reference Config.get_node*, config.py:116-144) ----
    # The node universe is static (like the reference's H1..H10 table),
    # so lookup tables and the ring order are computed once.

    def __post_init__(self):
        self._by_unique = {n.unique_name: n for n in self.nodes}
        self._ring = sorted(self.nodes, key=lambda n: (n.rank, n.host, n.port))
        # resolve group members (names or unique names) to unique
        # names once; membership must be known and disjoint — a chip
        # lent to two groups would double-count capacity
        self._group_members: Dict[str, Tuple[str, ...]] = {}
        self._group_by_member: Dict[str, WorkerGroupSpec] = {}
        self._group_roles: Dict[str, Dict[str, str]] = {}
        for g in self.worker_groups:
            resolved = []
            by_alias: Dict[str, str] = {}  # member-as-written -> unique
            for m in g.members:
                nid = self._by_unique.get(m) or self.node_by_name(m)
                if nid is None:
                    raise ValueError(
                        f"worker group {g.name!r}: unknown member {m!r}"
                    )
                resolved.append(nid.unique_name)
                by_alias[m] = nid.unique_name
            if len(set(resolved)) != len(resolved):
                raise ValueError(
                    f"worker group {g.name!r}: duplicate members"
                )
            for u in resolved:
                if u in self._group_by_member:
                    raise ValueError(
                        f"node {u} belongs to two worker groups "
                        f"({self._group_by_member[u].name!r}, {g.name!r})"
                    )
                self._group_by_member[u] = g
            self._group_members[g.name] = tuple(sorted(resolved))
            # disaggregation roles resolve to unique names too; a role
            # for a non-member (or an unknown role word) is a config
            # error, caught HERE like an unknown member — not at the
            # first mid-job prefill handoff
            roles: Dict[str, str] = {}
            for m, role in (g.roles or {}).items():
                u = by_alias.get(m)
                if u is None:
                    nid = self._by_unique.get(m) or self.node_by_name(m)
                    u = nid.unique_name if nid else None
                if u is None or u not in resolved:
                    raise ValueError(
                        f"worker group {g.name!r}: role for non-member "
                        f"{m!r}"
                    )
                if role not in ("prefill", "decode"):
                    raise ValueError(
                        f"worker group {g.name!r}: unknown role "
                        f"{role!r} for {m!r} (prefill|decode)"
                    )
                roles[u] = role
            self._group_roles[g.name] = roles

    def group_members_unique(self, name: str) -> Tuple[str, ...]:
        """A group's members as sorted unique names (the first is the
        group's deterministic primary)."""
        return self._group_members.get(name, ())

    def group_of_unique(self, unique_name: str) -> Optional[WorkerGroupSpec]:
        return self._group_by_member.get(unique_name)

    def group_roles_unique(self, name: str) -> Dict[str, str]:
        """A group's disaggregation roles keyed by unique name (empty
        when the group is not role-split)."""
        return dict(self._group_roles.get(name, {}))

    def node_by_unique_name(self, unique_name: str) -> Optional[NodeId]:
        return self._by_unique.get(unique_name)

    def ring(self) -> List[NodeId]:
        """The canonical ring order — the single definition consumed by
        both `ring_successors` and membership ping-target repair."""
        return self._ring

    def node_by_name(self, name: str) -> Optional[NodeId]:
        for n in self.nodes:
            if n.name == name:
                return n
        return None

    def ring_successors(self, node: NodeId) -> List[NodeId]:
        """The k ring successors this node pings.

        Reference hand-writes this per node (config.py:67-89); we
        compute it: each node pings the next k in `ring()` order.
        """
        ring = self.ring()
        if node not in ring:
            return []
        i = ring.index(node)
        k = min(self.ring_k, len(ring) - 1)
        return [ring[(i + j) % len(ring)] for j in range(1, k + 1)]

    def election_winner(self, alive: List[NodeId]) -> Optional[NodeId]:
        """Real bully winner: highest (rank, host, port) among the
        alive set. The reference *intended* this but hardcoded H2
        (election.py:24-32)."""
        if not alive:
            return None
        return max(alive, key=lambda n: (n.rank, n.host, n.port))

    # ---- serialization ----

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ClusterSpec":
        raw = json.loads(text)
        raw["nodes"] = [NodeId(**n) for n in raw.get("nodes", [])]
        if raw.get("introducer"):
            raw["introducer"] = NodeId(**raw["introducer"])
        if raw.get("timing"):
            raw["timing"] = Timing(**raw["timing"])
        if raw.get("store"):
            raw["store"] = StoreConfig(**raw["store"])
        if raw.get("mesh"):
            raw["mesh"] = MeshSpec(**raw["mesh"])
        raw["worker_groups"] = [
            WorkerGroupSpec(
                name=g["name"],
                members=tuple(g.get("members", ())),
                mesh=MeshSpec(**g["mesh"]) if g.get("mesh") else MeshSpec(),
                lm_models=tuple(g.get("lm_models", ())),
                roles=dict(g.get("roles", {}) or {}),
                hbm_bytes=int(g.get("hbm_bytes", 0) or 0),
            )
            for g in raw.get("worker_groups", [])
        ]
        return cls(**raw)

    @classmethod
    def from_file(cls, path: str) -> "ClusterSpec":
        with open(path) as f:
            return cls.from_json(f.read())

    @classmethod
    def localhost(
        cls,
        n: int,
        base_port: int = 8001,
        introducer_port: int = 8888,
        **kw,
    ) -> "ClusterSpec":
        """A local multi-process cluster on 127.0.0.1 ports — the
        pattern the reference used for testing (config.py:41-50,
        README.md:16-25), formalized as a first-class constructor."""
        nodes = [
            NodeId("127.0.0.1", base_port + i, name=f"H{i + 1}", rank=n - i)
            for i in range(n)
        ]
        intro = NodeId("127.0.0.1", introducer_port, name="DNS")
        return cls(nodes=nodes, introducer=intro, **kw)
