"""Cluster specification, node identity, and timing configuration.

Replaces the reference's hand-edited static tables (config.py:4-89,
nodes.py:1-35) with a declarative, serializable spec: no hardcoded
hostnames, no credential files (the reference reads SSH passwords from
password.txt, config.py:29-37 — our data plane is credential-free TCP),
and the ring topology is computed from the node list instead of written
out by hand (reference GLOBAL_RING_TOPOLOGY, config.py:67-89).
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac as _hmac
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass(frozen=True, order=True)
class NodeId:
    """Immutable node identity (reference: nodes.py Node).

    Ordering is (host, port) lexicographic; election rank uses
    `rank` when provided so operators can pin coordinator preference
    (the reference hardcoded H1 leader / H2 standby; we elect by
    highest rank with (host, port) as tiebreak).
    """

    host: str
    port: int
    name: str = ""
    rank: int = 0

    @property
    def unique_name(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return self.name or self.unique_name


@dataclass(frozen=True)
class Timing:
    """Failure-detector timing constants (reference config.py:4-10).

    Reference deployed values: ping every 12 s, ACK timeout 10 s,
    suspect cleanup 30 s, M=3 ring successors. Defaults here are the
    README's tuned example (README.md:68-78) scaled for tests; real
    deployments load their own.
    """

    ping_interval: float = 2.5
    ack_timeout: float = 2.0
    cleanup_time: float = 10.0
    missed_acks_to_suspect: int = 3
    leader_rpc_timeout: float = 20.0  # reference worker.py:1123-1135


@dataclass(frozen=True)
class StoreConfig:
    """Replicated-store knobs (reference leader.py:60, file_service.py:8-11)."""

    replication_factor: int = 4
    max_versions: int = 5
    root: str = "~/.dml_tpu/store"
    download_dir: str = "~/.dml_tpu/downloads"
    cleanup_on_startup: bool = False

    def store_path(self) -> str:
        return os.path.expanduser(self.root)

    def download_path(self) -> str:
        return os.path.expanduser(self.download_dir)


@dataclass(frozen=True)
class MeshSpec:
    """TPU device-mesh specification for the compute path.

    Axis sizes of -1 mean "fill with remaining devices". The inference
    engine shards batches over `dp`; model parallelism (when enabled)
    shards weights over `tp`; sequence parallelism (ring attention)
    uses `sp`.
    """

    dp: int = -1
    tp: int = 1
    sp: int = 1
    pp: int = 1  # pipeline stages (parallel/pipeline.py)
    ep: int = 1  # expert shards (parallel/moe.py)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {
            "dp": self.dp, "tp": self.tp, "sp": self.sp,
            "pp": self.pp, "ep": self.ep,
        }
        fixed = 1
        free = None
        for ax, s in sizes.items():
            if s == -1:
                if free is not None:
                    raise ValueError("only one mesh axis may be -1")
                free = ax
            else:
                fixed *= s
        if free is not None:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[free] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


@dataclass(frozen=True)
class WorkerGroupSpec:
    """A tensor-parallel serving group: nodes that pool their chips
    into ONE dp×tp worker (Kumar et al.'s pod-slice serving unit; the
    reference has no notion of this — every VM is its own whole-model
    replica, models.py:26,51).

    `members` are node names (e.g. "H4") or unique names; they must
    exist in the node table and belong to at most one group. `mesh` is
    the group's device layout over the pooled chips: `dp` shards
    batches, `tp` shards weight storage. The group serves as a single
    scheduler-visible worker (the deterministic first member by
    unique name is its primary) only while EVERY member is alive and
    schedulable; losing any member degrades it back to the surviving
    single-chip engines (jobs/groups.py).

    `lm_models` names LM serving models (register_lm names) this
    group's engine serves with weights RESIDENT tp-sharded in HBM
    (inference/lm_sharded.py). Like the member list it is static
    config: the coordinator reads it to decide whether an LM round
    may keep the group collapsed to one weighted slot — a group that
    does not declare the round's LM model falls back to single-chip
    slots for that round (the PR-5 behavior), because collapsing
    would model throughput the primary's engine cannot deliver.

    `roles` optionally splits the group into PREFILL and DECODE
    serving roles for disaggregated LM serving (member name ->
    "prefill" | "decode"). Prefill-role members run the chunked
    prompt prefill and hand the serialized KV-cache slab to the
    decode-role primary over the TCP store data plane; decode streams
    tokens through the normal job completion path. Empty = no
    disaggregation (every chip does both). Role assignment living
    HERE (not in a runtime protocol) means degradation/reform and
    failover derive the same view from spec + liveness, exactly like
    membership itself.

    A `mesh` with ``pp > 1`` serves the group's `lm_models` PIPELINE-
    parallel (inference/lm_sharded.py PipelinedLMBackend): each
    member holds only ``n_layers/pp`` of the layer stack, opening
    models DEEPER than one member's HBM. `hbm_bytes` (optional, 0 =
    unchecked) declares a member's HBM budget in bytes; the LM group
    wiring refuses to start a layout whose per-member weight bytes
    (`pp_hbm_report`) exceed it — a model bigger than the budget must
    be served through a pp axis, never silently OOM-ed at first
    batch."""

    name: str
    members: Tuple[str, ...] = ()
    mesh: MeshSpec = field(default_factory=lambda: MeshSpec(dp=-1, tp=1))
    lm_models: Tuple[str, ...] = ()
    roles: Dict[str, str] = field(default_factory=dict)
    hbm_bytes: int = 0


# ----------------------------------------------------------------------
# speculative-decoding knobs (inference/lm_server.py, lm_backend.py)
# ----------------------------------------------------------------------

#: default draft lookahead: tokens proposed per slot per verify round.
#: The verify forward streams the target weights ONCE for k+1 tokens,
#: so round cost grows sub-linearly in k while expected commit length
#: is ~(1-p^(k+1))/(1-p) at per-token acceptance p — k=4 captures most
#: of the win at p≈0.8 without paying long rejected tails.
SPEC_K_DEFAULT = 4

#: default break-even acceptance floor for automatic draft disable
#: (`lm_spec["spec_min_accept"]`): below ~1/3 acceptance a verify
#: round's expected commit (~rate*k + 1) no longer beats the chunk
#: scan's per-token cost plus the draft's own forward, so the server
#: reverts to plain decode (lm_specdec_disabled_total{reason=
#: "acceptance"}) instead of taxing every dispatch.
SPEC_MIN_ACCEPT_DEFAULT = 0.35

#: proposals measured before the acceptance gate may fire (and the
#: sliding-window grain thereafter) — one cold request's unlucky
#: prefix must not kill speculation for the server's lifetime.
SPEC_MIN_SAMPLES_DEFAULT = 64


def draft_lm_spec(
    lm_spec: Dict[str, Any], **overrides: Any
) -> Dict[str, Any]:
    """Derive a DRAFT-model spec from a target `lm_spec`: same family
    (vocab/dtype/heads — the draft must emit the target's token space),
    roughly quarter the compute (half the layers, half d_model/d_ff),
    deterministic weights from ``seed + 1`` so draft and target never
    silently share a tree. Serving-only keys (max_slots, chunk,
    spec_*, kv_cache_mb, weights ...) are dropped — the draft is a
    bare model spec for `lm_spec_parts`. ``overrides`` pin any field
    (`lm_spec["spec_draft"]` passes operator overrides through here).

    d_model halves but is re-aligned UP to a multiple of n_heads so
    head_dim stays integral for any target geometry."""
    heads = int(lm_spec.get("n_heads", 8))
    d_model = int(lm_spec["d_model"])
    d_half = max(heads, ((d_model // 2 + heads - 1) // heads) * heads)
    d_ff = int(lm_spec.get("d_ff", 4 * d_model))
    spec: Dict[str, Any] = {
        "name": f"{lm_spec.get('name', 'LM')}-draft",
        "vocab_size": int(lm_spec["vocab_size"]),
        "d_model": d_half,
        "n_heads": heads,
        "n_layers": max(1, int(lm_spec.get("n_layers", 2)) // 2),
        "d_ff": max(d_half, d_ff // 2),
        "dtype": lm_spec.get("dtype", "bfloat16"),
        "seed": int(lm_spec.get("seed", 0)) + 1,
    }
    if lm_spec.get("n_kv_heads") is not None:
        spec["n_kv_heads"] = int(lm_spec["n_kv_heads"])
    spec.update(overrides)
    return spec


# ----------------------------------------------------------------------
# authenticated-membership MACs (cluster/node.py join/leave protocol)
# ----------------------------------------------------------------------

#: bound on the retained universe-change log. Gossip catch-up and
#: rejoin deltas can only reach back this many changes; a node further
#: behind falls back to the `full` table form (JOIN_ACK / INTRODUCE_ACK
#: paths), which is authenticated as a whole instead of per entry.
UNIVERSE_LOG_CAP = 256


def _mac(secret: str, *parts: Any) -> str:
    msg = "\x1f".join(str(p) for p in parts).encode("utf-8")
    return _hmac.new(secret.encode("utf-8"), msg, hashlib.sha256).hexdigest()


def join_mac(secret: str, node: Dict[str, Any], nonce: str, epoch: int,
             group: str = "") -> str:
    """HMAC a JOIN_REQUEST: binds the joiner's identity + addr (host,
    port, name, rank), a fresh nonce (replay armor), the universe
    epoch the joiner believes current (stale-capture armor), AND the
    worker group it asks to be absorbed into ("" = plain slot) to the
    shared cluster secret. Forged, replayed, and stale-epoch joins
    all fail one of the bindings — and an on-path rewrite of the
    group field (a universe-log-recorded topology change) invalidates
    the MAC rather than re-shaping a mesh."""
    return _mac(
        secret, "join", node.get("host"), node.get("port"),
        node.get("name"), node.get("rank"), nonce, int(epoch),
        group or "",
    )


def leave_mac(secret: str, unique_name: str, nonce: str, epoch: int) -> str:
    """HMAC a LEAVE: proves the departing node (and not a spoofed
    sender evicting someone else) is asking to be retired."""
    return _mac(secret, "leave", unique_name, nonce, int(epoch))


def reply_mac(secret: str, nonce: str, epoch: int,
              universe: Optional[Dict[str, Any]] = None) -> str:
    """HMAC a JOIN_ACK (echoing the request nonce): the joiner only
    trusts epoch hints and universe tables that carry this, so a
    forged ACK can neither steer the joiner's epoch claim nor feed it
    a phantom node table."""
    blob = json.dumps(universe or {}, sort_keys=True,
                      separators=(",", ":"), default=str)
    return _mac(secret, "join-ack", nonce, int(epoch),
                hashlib.sha256(blob.encode("utf-8")).hexdigest())


def universe_entry_mac(secret: str, entry: Dict[str, Any]) -> str:
    """HMAC one universe-log entry (minted by the admitting leader,
    verified by every node that applies the entry from gossip):
    deterministic over the entry content, so independently-derived
    copies of the same change are identical."""
    node = entry.get("node") or {}
    return _mac(
        secret, "universe", int(entry.get("e", -1)), entry.get("op"),
        node.get("host"), node.get("port"), node.get("name"),
        node.get("rank"), entry.get("group") or "",
    )


@dataclass
class ClusterSpec:
    """The whole-cluster config: node table + ring + timing + store.

    The reference's equivalent is the hand-maintained H1..H10 table and
    GLOBAL_RING_TOPOLOGY dict (config.py:54-89), duplicated into
    `introduce process/config.py`. Here there is one spec, serializable
    to JSON, shared by every role including the introducer.

    The node table is the cluster's **universe**: byzantine hardening
    drops datagrams from senders outside it. With ``join_secret`` set,
    the universe becomes DYNAMIC — a now-versioned table
    (``universe_epoch``) that the leader may extend at runtime through
    the authenticated JOIN_REQUEST/LEAVE protocol (cluster/node.py):
    every change is an HMAC-stamped log entry that rides the gossip
    piggyback, so peers converge on the same table without trusting
    unauthenticated datagrams. With ``join_secret`` empty the table is
    static, exactly the pre-elastic behavior.
    """

    nodes: List[NodeId] = field(default_factory=list)
    introducer: Optional[NodeId] = None
    #: tensor-parallel serving groups (jobs/groups.py); empty = every
    #: node serves alone (the reference's one-replica-per-VM shape)
    worker_groups: List[WorkerGroupSpec] = field(default_factory=list)
    ring_k: int = 3  # number of ping successors (reference M=3, config.py:4)
    timing: Timing = field(default_factory=Timing)
    store: StoreConfig = field(default_factory=StoreConfig)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    testing: bool = False
    packet_drop_pct: float = 0.0  # loss-injection seam (reference protocol.py:10)
    # ---- gossip piggyback protocol (cluster/membership.py) ----
    # "delta": every PING/ACK carries a BOUNDED member subset — the
    # sender's own entry, the `gossip_delta_k` entries with the
    # highest recent-change priority (fewest piggybacks since their
    # status last changed, newest timestamp first), and a seeded
    # random tail of `gossip_delta_tail` others — with the FULL table
    # exchanged only at join (INTRODUCE_ACK), at the dead-peer
    # anti-entropy probe, and every `gossip_full_every`-th piggyback.
    # "full": the reference full-table piggyback (O(N) entries per
    # datagram — the measured baseline the scale bench scores
    # against). At small N (≤ 1 + k + tail members) delta mode emits
    # the full table anyway, so the protocols are bit-identical there.
    gossip_protocol: str = "delta"
    gossip_delta_k: int = 8
    gossip_delta_tail: int = 4
    gossip_full_every: int = 20
    # >0: the coordinator snapshots scheduler state into the store
    # every N seconds while jobs are in flight (full-restart survival
    # without operator-driven checkpoint-jobs); 0 disables
    jobs_checkpoint_interval: float = 0.0
    # ---- elastic membership (cluster/node.py join/leave protocol) ----
    # shared cluster secret authorizing runtime membership changes.
    # Empty (default) = joins disabled, the table is static and the
    # out-of-universe drops are final. Non-empty = nodes join through
    # JOIN_REQUEST (HMAC over identity+addr+nonce+epoch) and retire
    # through LEAVE; each admitted change bumps `universe_epoch` and
    # appends an HMAC-stamped log entry that gossip carries to peers.
    join_secret: str = ""
    #: version of the node table; bumps on every admitted join/leave
    universe_epoch: int = 0
    # ---- elastic cluster training (jobs/train.py) ----
    # fair-share weight of the `train` SLO class: below `batch` (1.0)
    # and far below `interactive` (3.0), so a TrainJob soaks idle
    # slots without queueing interactive work behind it
    train_class_weight: float = 0.5

    # ---- lookups (reference Config.get_node*, config.py:116-144) ----
    # Lookup tables and the ring order are recomputed by `_reindex`
    # whenever the universe changes (at construction, and on every
    # admitted join/leave).

    def __post_init__(self):
        #: HMAC-stamped change log: the catch-up payload gossip and
        #: JOIN_ACK/INTRODUCE_ACK ship to peers behind on the epoch
        self._universe_log: List[Dict[str, Any]] = []
        self._reindex()

    def _reindex(self) -> None:
        self._by_unique = {n.unique_name: n for n in self.nodes}
        self._ring = sorted(self.nodes, key=lambda n: (n.rank, n.host, n.port))
        # resolve group members (names or unique names) to unique
        # names once; membership must be known and disjoint — a chip
        # lent to two groups would double-count capacity
        self._group_members: Dict[str, Tuple[str, ...]] = {}
        self._group_by_member: Dict[str, WorkerGroupSpec] = {}
        self._group_roles: Dict[str, Dict[str, str]] = {}
        for g in self.worker_groups:
            resolved = []
            by_alias: Dict[str, str] = {}  # member-as-written -> unique
            for m in g.members:
                nid = self._by_unique.get(m) or self.node_by_name(m)
                if nid is None:
                    raise ValueError(
                        f"worker group {g.name!r}: unknown member {m!r}"
                    )
                resolved.append(nid.unique_name)
                by_alias[m] = nid.unique_name
            if len(set(resolved)) != len(resolved):
                raise ValueError(
                    f"worker group {g.name!r}: duplicate members"
                )
            for u in resolved:
                if u in self._group_by_member:
                    raise ValueError(
                        f"node {u} belongs to two worker groups "
                        f"({self._group_by_member[u].name!r}, {g.name!r})"
                    )
                self._group_by_member[u] = g
            self._group_members[g.name] = tuple(sorted(resolved))
            # disaggregation roles resolve to unique names too; a role
            # for a non-member (or an unknown role word) is a config
            # error, caught HERE like an unknown member — not at the
            # first mid-job prefill handoff
            roles: Dict[str, str] = {}
            for m, role in (g.roles or {}).items():
                u = by_alias.get(m)
                if u is None:
                    nid = self._by_unique.get(m) or self.node_by_name(m)
                    u = nid.unique_name if nid else None
                if u is None or u not in resolved:
                    raise ValueError(
                        f"worker group {g.name!r}: role for non-member "
                        f"{m!r}"
                    )
                if role not in ("prefill", "decode"):
                    raise ValueError(
                        f"worker group {g.name!r}: unknown role "
                        f"{role!r} for {m!r} (prefill|decode)"
                    )
                roles[u] = role
            self._group_roles[g.name] = roles

    def group_members_unique(self, name: str) -> Tuple[str, ...]:
        """A group's members as sorted unique names (the first is the
        group's deterministic primary)."""
        return self._group_members.get(name, ())

    def group_of_unique(self, unique_name: str) -> Optional[WorkerGroupSpec]:
        return self._group_by_member.get(unique_name)

    def group_roles_unique(self, name: str) -> Dict[str, str]:
        """A group's disaggregation roles keyed by unique name (empty
        when the group is not role-split)."""
        return dict(self._group_roles.get(name, {}))

    def node_by_unique_name(self, unique_name: str) -> Optional[NodeId]:
        return self._by_unique.get(unique_name)

    def ring(self) -> List[NodeId]:
        """The canonical ring order — the single definition consumed by
        both `ring_successors` and membership ping-target repair."""
        return self._ring

    def node_by_name(self, name: str) -> Optional[NodeId]:
        for n in self.nodes:
            if n.name == name:
                return n
        return None

    def ring_successors(self, node: NodeId) -> List[NodeId]:
        """The k ring successors this node pings.

        Reference hand-writes this per node (config.py:67-89); we
        compute it: each node pings the next k in `ring()` order.
        """
        ring = self.ring()
        if node not in ring:
            return []
        i = ring.index(node)
        k = min(self.ring_k, len(ring) - 1)
        return [ring[(i + j) % len(ring)] for j in range(1, k + 1)]

    def election_winner(self, alive: List[NodeId]) -> Optional[NodeId]:
        """Real bully winner: highest (rank, host, port) among the
        alive set. The reference *intended* this but hardcoded H2
        (election.py:24-32)."""
        if not alive:
            return None
        return max(alive, key=lambda n: (n.rank, n.host, n.port))

    # ---- dynamic universe (authenticated runtime join/leave) ----

    @staticmethod
    def _node_dict(node: NodeId) -> Dict[str, Any]:
        return {"host": node.host, "port": node.port,
                "name": node.name, "rank": node.rank}

    @staticmethod
    def node_from_dict(d: Any) -> Optional[NodeId]:
        """A NodeId from wire-supplied fields, or None when the
        payload is garbled/byzantine (wrong types, missing keys)."""
        if not isinstance(d, dict):
            return None
        try:
            host = d["host"]
            port = int(d["port"])
            name = str(d.get("name", "") or "")
            rank = int(d.get("rank", 0) or 0)
        except (KeyError, TypeError, ValueError):
            return None
        if not isinstance(host, str) or not host or not (0 < port < 65536):
            return None
        return NodeId(host, port, name=name, rank=rank)

    def _append_universe_entry(self, entry: Dict[str, Any]) -> None:
        self._universe_log.append(entry)
        if len(self._universe_log) > UNIVERSE_LOG_CAP:
            del self._universe_log[: len(self._universe_log)
                                   - UNIVERSE_LOG_CAP]

    def add_node(
        self,
        node: NodeId,
        group: Optional[str] = None,
        local: bool = False,
    ) -> bool:
        """Admit `node` into the universe (leader-side of an
        authenticated join). Already-known nodes are a no-op rejoin
        (False — no epoch bump). `group` absorbs the joiner into that
        worker group's member list (under-formed groups regain
        capacity through the reform ladder, jobs/groups.py).

        ``local=True`` records the node WITHOUT minting a change
        entry or bumping the epoch — a joiner pre-seeding its own
        table ("I know myself; the cluster assigns the epoch") and
        operator bookkeeping use this form."""
        if node.unique_name in self._by_unique:
            return False
        if group is not None:
            gi = next(
                (i for i, g in enumerate(self.worker_groups)
                 if g.name == group), None)
            if gi is None:
                raise ValueError(f"unknown worker group {group!r}")
            self.worker_groups[gi] = dataclasses.replace(
                self.worker_groups[gi],
                members=self.worker_groups[gi].members
                + (node.unique_name,),
            )
        self.nodes.append(node)
        if not local:
            self.universe_epoch += 1
            entry: Dict[str, Any] = {
                "e": self.universe_epoch, "op": "join",
                "node": self._node_dict(node),
            }
            if group:
                entry["group"] = group
            if self.join_secret:
                entry["mac"] = universe_entry_mac(self.join_secret, entry)
            self._append_universe_entry(entry)
        self._reindex()
        return True

    def _strip_from_groups(self, unique_name: str) -> None:
        def resolves_to(member: str) -> bool:
            nid = self._by_unique.get(member) or self.node_by_name(member)
            return nid is not None and nid.unique_name == unique_name

        for i, g in enumerate(self.worker_groups):
            if unique_name not in self._group_members.get(g.name, ()):
                continue
            keep = tuple(m for m in g.members if not resolves_to(m))
            roles = {m: r for m, r in (g.roles or {}).items()
                     if not resolves_to(m)}
            self.worker_groups[i] = dataclasses.replace(
                g, members=keep, roles=roles)

    def remove_node(self, unique_name: str, local: bool = False) -> bool:
        """Retire `unique_name` from the universe (graceful LEAVE, or
        applying a peer's leave entry). Strips the node from any
        worker group it lent chips to — the group's remaining members
        ARE its new full strength, which is how a scale-in re-shapes
        group topology instead of reading as a permanent degradation."""
        node = self._by_unique.get(unique_name)
        if node is None:
            return False
        self._strip_from_groups(unique_name)
        self.nodes = [n for n in self.nodes
                      if n.unique_name != unique_name]
        if not local:
            self.universe_epoch += 1
            entry: Dict[str, Any] = {
                "e": self.universe_epoch, "op": "leave",
                "node": self._node_dict(node),
            }
            if self.join_secret:
                entry["mac"] = universe_entry_mac(self.join_secret, entry)
            self._append_universe_entry(entry)
        self._reindex()
        return True

    def universe_delta(self, since: int, max_entries: int = 64) -> Dict[str, Any]:
        """The catch-up payload for a peer at epoch `since`: a
        contiguous WINDOW of up to `max_entries` HMAC-stamped change
        entries starting right past the peer's epoch. A peer far
        behind catches up incrementally — each exchange advances it
        `max_entries` epochs and the next exchange ships the next
        window — so the bounded gossip piggyback converges any gap
        the retained log covers. Only when the log no longer reaches
        back to ``since + 1`` (> UNIVERSE_LOG_CAP changes behind)
        does this fall to the FULL table form (nodes + worker groups
        — accepted only on authenticated reply paths, where the
        enclosing reply MAC covers it)."""
        since = max(int(since), 0)
        if since >= self.universe_epoch:
            return {"e": self.universe_epoch, "log": []}
        entries = [e for e in self._universe_log if e["e"] > since]
        if entries and entries[0]["e"] == since + 1:
            # the log is contiguous by construction (epochs increment
            # by one per entry; the cap trims only the FRONT), so any
            # prefix of this slice is applicable as-is
            return {"e": self.universe_epoch,
                    "log": list(entries[:max(1, max_entries)])}
        return {
            "e": self.universe_epoch,
            "full": {
                "nodes": [self._node_dict(n) for n in self.nodes],
                "worker_groups": [
                    {"name": g.name, "members": list(g.members)}
                    for g in self.worker_groups
                ],
            },
        }

    def _apply_entry(self, ent: Dict[str, Any]) -> None:
        node = self.node_from_dict(ent.get("node"))
        if node is None:
            return
        if ent.get("op") == "join":
            group = ent.get("group")
            if group is not None and not any(
                g.name == group for g in self.worker_groups
            ):
                group = None  # unknown group here: plain slot
            if node.unique_name not in self._by_unique:
                try:
                    self.add_node(node, group=group, local=True)
                except ValueError:
                    self.add_node(node, local=True)
            elif group is not None and node.unique_name not in \
                    self.group_members_unique(group):
                # already in the table (a joiner pre-seeds itself
                # locally) but the admission absorbed it into a
                # group: the membership must still land
                gi = next(i for i, g in enumerate(self.worker_groups)
                          if g.name == group)
                self.worker_groups[gi] = dataclasses.replace(
                    self.worker_groups[gi],
                    members=self.worker_groups[gi].members
                    + (node.unique_name,),
                )
                self._reindex()
        elif ent.get("op") == "leave":
            self.remove_node(node.unique_name, local=True)

    def apply_universe(
        self, delta: Any, verified: bool = False
    ) -> bool:
        """Fold a peer's universe catch-up into this spec. Log entries
        verify their own HMAC stamp (unless ``verified`` — the caller
        already authenticated the enclosing reply); a bad stamp or a
        gap stops the application (we stay behind and catch up from a
        healthier peer). The `full` form is accepted only when
        ``verified``. Returns True when the table or epoch changed."""
        if not isinstance(delta, dict):
            return False
        changed = False
        full = delta.get("full")
        if isinstance(full, dict):
            if not verified:
                return False  # full tables only ride authenticated paths
            try:
                e = int(delta.get("e", 0))
            except (TypeError, ValueError):
                return False
            if e <= self.universe_epoch:
                return False
            nodes = [
                n for n in (
                    self.node_from_dict(d)
                    for d in full.get("nodes", [])
                    if isinstance(d, dict)
                ) if n is not None
            ]
            if not nodes:
                return False
            members_by_group = {
                g.get("name"): list(g.get("members", []))
                for g in full.get("worker_groups", [])
                if isinstance(g, dict)
            }
            known = {n.unique_name for n in nodes}
            self.nodes = nodes
            self.worker_groups = [
                dataclasses.replace(
                    g,
                    members=tuple(
                        m for m in members_by_group.get(
                            g.name, list(g.members))
                        if m in known or m in {n.name for n in nodes}
                    ),
                    roles={m: r for m, r in (g.roles or {}).items()
                           if m in known},
                )
                for g in self.worker_groups
            ]
            self.universe_epoch = e
            self._universe_log = []  # history predating the snapshot
            self._reindex()
            return True
        log_entries = delta.get("log")
        if not isinstance(log_entries, list):
            return False
        for ent in sorted(
            (e for e in log_entries if isinstance(e, dict)),
            key=lambda e: e.get("e", 0)
            if isinstance(e.get("e"), int) else 0,
        ):
            e = ent.get("e")
            if not isinstance(e, int) or e <= self.universe_epoch:
                continue
            if e != self.universe_epoch + 1:
                break  # gap: stay behind, catch up from a longer log
            if self.join_secret and not verified:
                want = universe_entry_mac(self.join_secret, ent)
                got = ent.get("mac")
                if not isinstance(got, str) or not _hmac.compare_digest(
                    got, want
                ):
                    break  # unstamped/forged entry: refuse the tail too
            self._apply_entry(ent)
            self.universe_epoch = e
            self._append_universe_entry(dict(ent))
            changed = True
        return changed

    # ---- serialization ----

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ClusterSpec":
        raw = json.loads(text)
        raw["nodes"] = [NodeId(**n) for n in raw.get("nodes", [])]
        if raw.get("introducer"):
            raw["introducer"] = NodeId(**raw["introducer"])
        if raw.get("timing"):
            raw["timing"] = Timing(**raw["timing"])
        if raw.get("store"):
            raw["store"] = StoreConfig(**raw["store"])
        if raw.get("mesh"):
            raw["mesh"] = MeshSpec(**raw["mesh"])
        raw["worker_groups"] = [
            WorkerGroupSpec(
                name=g["name"],
                members=tuple(g.get("members", ())),
                mesh=MeshSpec(**g["mesh"]) if g.get("mesh") else MeshSpec(),
                lm_models=tuple(g.get("lm_models", ())),
                roles=dict(g.get("roles", {}) or {}),
                hbm_bytes=int(g.get("hbm_bytes", 0) or 0),
            )
            for g in raw.get("worker_groups", [])
        ]
        return cls(**raw)

    @classmethod
    def from_file(cls, path: str) -> "ClusterSpec":
        with open(path) as f:
            return cls.from_json(f.read())

    @classmethod
    def localhost(
        cls,
        n: int,
        base_port: int = 8001,
        introducer_port: int = 8888,
        **kw,
    ) -> "ClusterSpec":
        """A local multi-process cluster on 127.0.0.1 ports — the
        pattern the reference used for testing (config.py:41-50,
        README.md:16-25), formalized as a first-class constructor."""
        nodes = [
            NodeId("127.0.0.1", base_port + i, name=f"H{i + 1}", rank=n - i)
            for i in range(n)
        ]
        intro = NodeId("127.0.0.1", introducer_port, name="DNS")
        return cls(nodes=nodes, introducer=intro, **kw)
