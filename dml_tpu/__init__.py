"""dml_tpu — a TPU-native distributed ML inference framework.

A ground-up rebuild of the capabilities of
shahzadjutt123/Distributed-Machine-Learning ("awesomedml"):

- SWIM-style gossip failure detection over a configurable ring
  (reference: membershipList.py, worker.py:1181-1199)
- leader election with a hot-standby coordinator
  (reference: election.py, worker.py:887-919)
- a replicated, versioned distributed file store
  (reference: file_service.py, leader.py)
- a cost-model-driven fair-share batch inference scheduler with
  preemption and failure recovery (reference: worker.py:255-495)
- C1-C5 query-rate / latency metrics and an interactive CLI
  (reference: worker.py:1629-2034)

The compute path is idiomatic JAX/XLA: Flax model definitions,
jit-compiled bfloat16 batched forward passes on TPU, fixed shapes,
`jax.sharding.Mesh` + pjit for multi-chip parallelism, and Pallas
kernels for fused host-side-free preprocessing. The control plane is
a lightweight asyncio UDP protocol over DCN; the bulk data plane is
TCP streams (replacing the reference's scp-over-SSH).
"""

__version__ = "0.1.0"
